"""Roofline/MFU attribution: the modeled-vs-measured efficiency join.

ROADMAP item 4 opens with "Transformer MFU stands at 0.631" — a number
a bench round computed by hand. This module makes the framework able to
say it about ITSELF, per compile signature, live: the compile registry
(``profiler.record_compile``, fed by ``fused_step._record_compile``)
already holds the MODELED side of every program — cost-analysis flops
and bytes_accessed, HLO-measured collective payload, the comm_model's
wire time — and the watchdog step beacon measures every step's wall
clock. Nothing joined them. This module is that join.

Per hot signature it derives, at drain time:

``mfu``            flops / (median step time x peak FLOP/s for the
                   program's dominant dtype — the
                   ``comm_model.ASSUMPTIONS`` peak table)
``membw_util``     bytes_accessed / (median step time x HBM bandwidth)
``intensity``      arithmetic intensity, flops / bytes_accessed
roofline verdict   which term binds the step: ``compute`` / ``memory``
                   / ``comm`` / ``overhead``. The first three are the
                   modeled lower bounds (compute and memory overlap on
                   the chip, so the modeled device time is
                   ``max(t_compute, t_mem) + t_comm``, the comm term
                   priced through ``comm_model.allreduce_seconds`` at
                   the recording site); ``overhead`` is the residual of
                   MEASURED median time over that modeled floor — the
                   host/dispatch share no roofline explains.

Price engineering (the PR 12/14 drain-time discipline): the hot path is
ONE GIL-atomic ``deque.append`` of a ``(sig, dur_s)`` tuple riding the
watchdog beacon's OWN clock reads — no lock, no new ``monotonic()``.
The modeled side arrives at compile time (rare, expensive anyway)
through :func:`note_compile` from the ``record_compile`` choke point.
ALL math folds under one named lock (``perfmodel.state``) at drain, on
whoever asks: the watchdog poller each pass, ``metrics()``, a
flight-record dump, ``close_run``. ``BENCH_MODEL=perf_attrib`` prices
the hot shape at <0.5% of a fused step.

Efficiency-collapse detector (memwatch latch idiom): a step whose MFU
drops below ``MXTPU_PERF_MFU_DROP`` x the signature's own rolling
median trips ONE ``perf`` flight-record dump per episode, naming the
signature and which roofline term grew (the modeled terms are constants
between compiles, so the growth is the overhead residual — unless a
re-record moved a modeled term, which the dump's term table shows).
Collapsed steps stay OUT of the rolling windows: a sustained collapse
must not drag its own baseline down and self-heal the alarm. The latch
re-arms on the first clean step.

Surfaces: ``metrics()['perf']`` (registered provider), the dumps()
Roofline table, ``mxtpu_mfu{signature=}`` / ``mxtpu_roofline_bound``
Prometheus families, a ``metadata.perf`` block in every flight-record
dump, a per-signature ``perf`` block in goodput run manifests and every
``bench.py`` manifest, and ``tools/perf_report.py`` (``--compare`` is
the standing cross-run MFU regression gate).

Nothing here touches a traced value: ``MXTPU_PERF=1`` training is
bitwise-identical to ``MXTPU_PERF=0`` (pinned in tests).

Env knobs (docs/ENV_VARS.md): ``MXTPU_PERF`` (default 1),
``MXTPU_PERF_WINDOW`` (32), ``MXTPU_PERF_MFU_DROP`` (0.5),
``MXTPU_PERF_MIN_SAMPLES`` (5).
"""
from __future__ import annotations

import collections
import statistics

from . import flightrec as _flightrec
from . import locktrace as _locktrace
from ..base import getenv as _getenv
from .watchdog import _envf

__all__ = [
    "ENABLED", "SCHEMA", "BOUNDS", "configure", "reset",
    "note_compile", "note_step", "fold_pending", "snapshot", "table",
    "manifest_block",
]

ENABLED = _getenv("MXTPU_PERF", "1") not in ("0", "false", "off")

SCHEMA = "mxtpu.perf/1"

# the roofline verdict vocabulary, in tie-break order (a tie goes to
# the more actionable/modeled term)
BOUNDS = ("compute", "memory", "comm", "overhead")

_lock = _locktrace.named_lock("perfmodel.state")

# hot-path mailbox (the goodput _PENDING idiom): (sig, dur_s) tuples,
# appended by watchdog.step_end AFTER it releases its own lock, riding
# the beacon's already-computed duration
_PENDING = collections.deque()  # mxlint: disable=MX003 (GIL-atomic deque appends on the per-step hot path; all join math folds under _lock at drain — the goodput-ledger idiom)
_FOLD_AT = 1 << 17  # backstop only: the watchdog poller drains each pass

_MODELS_CAP = 256   # modeled-side entries (compile registry mirror)
_MEAS_CAP = 64      # measured-side signatures (hot sigs are few)

_cfg = {}
_models = {}   # sig -> modeled dict (flops, bytes, comm, peak, ...)
_meas = {}     # sig -> measured accumulator (windows, counts, latch)  # mxlint: disable=MX003 (mutated only from _fold_locked, which every caller runs under _lock)
_stats = {"steps": 0, "collapses": 0, "collapse_dumps": 0,  # mxlint: disable=MX003 (same _fold_locked contract as _meas)
          "dropped_sigs": 0}


def _defaults():
    return {
        "window": max(2, int(_envf("MXTPU_PERF_WINDOW", 32))),
        "mfu_drop": _envf("MXTPU_PERF_MFU_DROP", 0.5),
        "min_samples": max(2, int(_envf("MXTPU_PERF_MIN_SAMPLES", 5))),
    }


_cfg.update(_defaults())


def configure(enabled=None, window=None, mfu_drop=None,
              min_samples=None):
    """Override the env-derived knobs at runtime (tests, notebooks)."""
    global ENABLED
    with _lock:
        if window is not None:
            _cfg["window"] = max(2, int(window))
            for st in _meas.values():
                st["durs"] = collections.deque(
                    st["durs"], maxlen=_cfg["window"])
                st["mfus"] = collections.deque(
                    st["mfus"], maxlen=_cfg["window"])
        if mfu_drop is not None:
            _cfg["mfu_drop"] = float(mfu_drop)
        if min_samples is not None:
            _cfg["min_samples"] = max(2, int(min_samples))
    if enabled is not None:
        ENABLED = bool(enabled)


def reset():
    """Clear all state; knobs re-read from the env (test isolation)."""
    global ENABLED
    with _lock:
        _models.clear()
        _meas.clear()
        _PENDING.clear()
        for k in _stats:
            _stats[k] = 0
        _cfg.clear()
        _cfg.update(_defaults())
    ENABLED = _getenv("MXTPU_PERF", "1") not in ("0", "false", "off")


def _assumptions():
    """The hardware model (lazy: ``benchmark/comm_model.py`` loaded by
    path through the fused step's cached loader; ``None`` in an
    installed wheel without the benchmark dir — rows then carry counts
    and times but no memory-bandwidth utilization)."""
    try:
        from ..gluon.fused_step import _load_comm_model
        cm = _load_comm_model()
        return cm.ASSUMPTIONS if cm is not None else None
    except Exception:
        return None


# -- feeds -------------------------------------------------------------------

def note_compile(name, key, flops=None, bytes_accessed=None,
                 comm_bytes=None, modeled_comm_us=None, args=None):
    """The modeled side: one compile-registry record (called from
    ``profiler.record_compile`` — compiles are rare, so this takes the
    lock). The signature tag is ``name:key``, the same tag the fused
    step threads through ``watchdog.step_end`` so the measured side
    joins exactly. ``args`` carries the recording site's extras
    (``dtype``/``peak_tflops``/``dp`` from the fused step)."""
    if not ENABLED or key is None:
        return
    sig = "%s:%s" % (name, key)
    args = args or {}
    with _lock:
        if sig not in _models and len(_models) >= _MODELS_CAP:
            # evict entries that never joined a measured step first
            for k in [k for k in _models if k not in _meas]:
                del _models[k]
            if len(_models) >= _MODELS_CAP:
                _models.clear()
        _models[sig] = {
            "name": str(name),
            "flops": float(flops) if flops else None,
            "bytes_accessed":
                float(bytes_accessed) if bytes_accessed else None,
            "comm_bytes": float(comm_bytes) if comm_bytes else None,
            "comm_s": (float(modeled_comm_us) / 1e6
                       if modeled_comm_us is not None else None),
            "peak_tflops": args.get("peak_tflops"),
            "dtype": args.get("dtype"),
            "dp": args.get("dp"),
        }


def note_step(sig, dur_s):
    """The measured side: one completed fused step for signature
    ``sig`` (the watchdog beacon feed — its already-computed duration;
    no lock, no clock read, one GIL-atomic append)."""
    if not ENABLED:
        return
    _PENDING.append((sig, dur_s))
    if len(_PENDING) >= _FOLD_AT:
        fold_pending()


# -- drain -------------------------------------------------------------------

def _mfu_of(model, dur_s):
    flops, peak = model.get("flops"), model.get("peak_tflops")
    if not flops or not peak or dur_s <= 0:
        return None
    return flops / (dur_s * peak * 1e12)


def _fold_locked():
    """Drain the mailbox: per-sig windows, per-step MFU, and the
    collapse latch. Returns dump requests to fire AFTER the lock is
    released (a flight-record dump must never run under a subsystem
    lock). popleft races benignly with concurrent appends."""
    dumps = []
    while _PENDING:
        sig, dur = _PENDING.popleft()
        st = _meas.get(sig)
        if st is None:
            if len(_meas) >= _MEAS_CAP:
                _stats["dropped_sigs"] += 1
                continue
            st = _meas[sig] = {
                "count": 0, "sum_s": 0.0, "last_s": 0.0,
                "durs": collections.deque(maxlen=_cfg["window"]),
                "mfus": collections.deque(maxlen=_cfg["window"]),
                "collapses": 0, "tripped": False,
            }
        st["count"] += 1
        st["sum_s"] += dur
        st["last_s"] = dur
        _stats["steps"] += 1
        model = _models.get(sig)
        mfu = _mfu_of(model, dur) if model else None
        collapsed = False
        if mfu is not None and \
                len(st["mfus"]) >= _cfg["min_samples"]:
            baseline = statistics.median(st["mfus"])
            if mfu < _cfg["mfu_drop"] * baseline:
                collapsed = True
                st["collapses"] += 1
                _stats["collapses"] += 1
                if not st["tripped"]:
                    # latch: ONE dump per episode (memwatch idiom)
                    st["tripped"] = True
                    dumps.append(_trip_info(sig, st, model, dur,
                                            mfu, baseline))
        if collapsed:
            # a collapsed step stays OUT of the windows: a sustained
            # collapse must not drag its own baseline down and
            # self-heal the alarm
            continue
        if st["tripped"]:
            st["tripped"] = False  # clean step: episode over, re-arm
        st["durs"].append(dur)
        if mfu is not None:
            st["mfus"].append(mfu)
    return dumps


def _trip_info(sig, st, model, dur, mfu, baseline):
    """Trip payload for the collapse dump: the full roofline term
    table at the tripping duration vs the baseline median, naming
    which term grew (the modeled terms are per-compile constants, so
    between compiles the delta is all overhead — a re-record that
    moved a modeled term shows up in the table instead)."""
    base_med = statistics.median(st["durs"]) if st["durs"] else dur
    now = _terms(model, dur)
    base = _terms(model, base_med)
    grew, grew_by = "overhead", 0.0
    for b in BOUNDS:
        d = now.get(b, 0.0) - base.get(b, 0.0)
        if d > grew_by:
            grew, grew_by = b, d
    return {
        "signature": sig, "mfu": round(mfu, 6),
        "median_mfu": round(baseline, 6),
        "drop_threshold": _cfg["mfu_drop"],
        "measured_s": round(dur, 6),
        "baseline_median_s": round(base_med, 6),
        "grew": grew, "grew_by_s": round(grew_by, 9),
        "terms_s": {b: round(now.get(b, 0.0), 9) for b in BOUNDS},
    }


def _terms(model, dur_s):
    """The roofline decomposition of one measured duration against a
    signature's modeled costs: compute and memory lower bounds (they
    overlap on-chip, so the modeled device floor is their max), the
    comm term (priced via ``comm_model.allreduce_seconds`` at the
    recording site), and the overhead residual."""
    a = _assumptions()
    out = {}
    flops, peak = model.get("flops"), model.get("peak_tflops")
    if flops and not peak and a:
        peak = a.get("peak_tflops", {}).get("bf16")
    out["compute"] = (flops / (peak * 1e12)
                      if flops and peak else 0.0)
    b = model.get("bytes_accessed")
    bw = a.get("hbm_bw_GBps") if a else None
    out["memory"] = b / (bw * 1e9) if b and bw else 0.0
    out["comm"] = model.get("comm_s") or 0.0
    floor = max(out["compute"], out["memory"]) + out["comm"]
    out["overhead"] = max(0.0, dur_s - floor)
    return out


def fold_pending():
    """Fold the hot-path mailbox — called by the watchdog poller each
    pass, every snapshot, and the size backstop. Collapse dumps fire
    here, outside the lock."""
    with _lock:
        dumps = _fold_locked()
    for info in dumps:
        path = _flightrec.dump("perf", extra=info, swallow=True)
        if path is not None:
            with _lock:
                _stats["collapse_dumps"] += 1


# -- derived surfaces --------------------------------------------------------

def _row_locked(sig, st):
    model = _models.get(sig) or {}
    med = statistics.median(st["durs"]) if st["durs"] else \
        (st["last_s"] or None)
    row = {
        "sig": sig,
        "steps": st["count"],
        "collapses": st["collapses"],
        "median_s": med,
        "mean_s": st["sum_s"] / st["count"] if st["count"] else None,
        "flops": model.get("flops"),
        "bytes_accessed": model.get("bytes_accessed"),
        "comm_bytes": model.get("comm_bytes"),
        "peak_tflops": model.get("peak_tflops"),
        "dtype": model.get("dtype"),
        "mfu": None, "membw_util": None, "intensity": None,
        "bound": None, "terms_s": None,
    }
    if model and med:
        terms = _terms(model, med)
        row["terms_s"] = {b: terms[b] for b in BOUNDS}
        row["mfu"] = _mfu_of(model, med)
        if terms["memory"] > 0:
            row["membw_util"] = terms["memory"] / med
        if model.get("flops") and model.get("bytes_accessed"):
            row["intensity"] = model["flops"] / model["bytes_accessed"]
        row["bound"] = max(BOUNDS, key=lambda b: terms[b])
    return row


def table():
    """Joined per-signature rows, hottest first — the dumps() Roofline
    table, the Prometheus families, and the manifest perf block all
    render from this one list."""
    with _lock:
        _fold_locked()  # cheap; dump firing is the poller's job
        rows = [_row_locked(sig, st) for sig, st in _meas.items()]
    rows.sort(key=lambda r: -r["steps"])
    return rows


def snapshot():
    """``metrics()['perf']``: flat top-level counters plus the
    per-signature join under ``per_signature`` (JSON-safe; the
    Prometheus exporter takes only the numeric top-level keys — the
    per-sig gauges have their own ``mxtpu_mfu``/``mxtpu_roofline_bound``
    families)."""
    rows = table()
    out = {"enabled": int(ENABLED), "signatures": len(rows)}
    with _lock:
        out.update(_stats)
    joined = [r for r in rows if r["mfu"] is not None]
    if joined:
        hot = joined[0]  # hottest joined signature: the headline gauge
        out["mfu"] = round(hot["mfu"], 6)
        out["hot_signature"] = hot["sig"]
        if hot["bound"]:
            out["hot_bound"] = hot["bound"]
    out["per_signature"] = {
        r["sig"]: {k: (round(v, 9) if isinstance(v, float) else v)
                   for k, v in r.items() if k != "sig"}
        for r in rows}
    return out


def manifest_block():
    """The ``perf`` block embedded in goodput run manifests and bench
    manifests — what ``tools/perf_report.py`` renders and compares.
    ``None`` when nothing joined (a manifest without the block is a
    run that never ran a tagged fused step)."""
    rows = [r for r in table() if r["mfu"] is not None]
    if not rows:
        return None
    a = _assumptions()
    return {
        "schema": SCHEMA,
        "assumptions": {
            k: a.get(k) for k in ("chip", "peak_tflops", "hbm_bw_GBps")
        } if a else None,
        "signatures": {
            r["sig"]: {
                "steps": r["steps"],
                "median_s": r["median_s"],
                "mfu": r["mfu"],
                "membw_util": r["membw_util"],
                "intensity": r["intensity"],
                "bound": r["bound"],
                "terms_s": r["terms_s"],
                "flops": r["flops"],
                "bytes_accessed": r["bytes_accessed"],
                "comm_bytes": r["comm_bytes"],
                "peak_tflops": r["peak_tflops"],
                "dtype": r["dtype"],
                "collapses": r["collapses"],
            } for r in rows},
    }


# registered at import like the watchdog/goodput providers: every
# process that loads the telemetry stack carries metrics()['perf']
from .. import profiler as _profiler  # noqa: E402

_profiler.register_stats_provider("perf", snapshot)
