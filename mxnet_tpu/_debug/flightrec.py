"""Always-on flight recorder: the black box under the telemetry plane.

PRs 2 and 6 built a rich *opt-in* profiler (trace lanes, histograms,
/metrics) — but every diagnostic dies with the process. When a run
crashes, wedges in a collective, or one rank straggles, there is
nothing to read afterward unless a profile run happened to be active.
This module keeps a fixed-size, lock-light ring buffer of the most
recent spans/counters/markers that is **always on** (independent of
``profiler.set_state``) and dumps it — atomically, temp+rename — as a
chrome-trace shard the moment something goes wrong:

========================  ===================================================
unhandled exception       ``sys.excepthook`` chain (and
                          ``threading.excepthook`` for worker threads)
fatal signal              ``faulthandler`` is enabled into
                          ``flightrec_r<rank>_fatal.txt`` next to the shards
                          (SIGSEGV/SIGABRT cannot run Python — the native
                          stack file is the post-mortem for those)
on demand                 ``SIGUSR2`` (``kill -USR2 <pid>``) — loss- and
                          bitwise-neutral; the run continues
watchdog trip             a stalled/straggling step
                          (``mxnet_tpu._debug.watchdog``)
========================  ===================================================

Each dump bundles the ring (rendered as chrome-trace events on the
profiler's lanes and timebase, so ``tools/trace_merge.py`` merges a
flight-record shard with live profiler shards into one timeline), all-
thread Python stacks, ``profiler.metrics()`` (which carries the
elastic/fault/watchdog provider sections), the faultpoint trigger
counters, and any registered context (``set_context`` — the elastic
controller publishes its committed world here).

Hot-path contract (the reason this can be always on): the instrumented
sites share the profiler's ONE inlined guard — ``_HOOKS and
_profiler._LIVE`` where ``_LIVE = _ACTIVE or flightrec.ENABLED`` — so
there is no second branch on the dispatch path (mxlint MX011), and the
record itself is one append into a ``collections.deque(maxlen=N)`` (a
C ring buffer; append is GIL-atomic, no lock). On the per-op dispatch
path the append is a BARE OP NAME with no clock read — a
``time.perf_counter()`` pair alone costs ~3x the whole budget per op —
and dump-time rendering anchors each bare-name breadcrumb to the
nearest timestamped neighbor (bulk flushes, step spans, markers and
counters all carry real timestamps, so anchors are dense in any real
workload). ``BENCH_MODEL=flightrec_overhead`` gates the ring at <0.5%
of eager dispatch and <0.1% of fused-step time.

Env knobs (docs/ENV_VARS.md):

- ``MXTPU_FLIGHTREC`` (default 1): master switch.
- ``MXTPU_FLIGHTREC_EVENTS`` (default 4096): ring capacity.
- ``MXTPU_FLIGHTREC_DIR`` (default ``./flightrec/``, created lazily at
  the first write): where shards land. Dumps used to land bare in the
  CWD, which litters repos and working trees (ISSUE 13 satellite).
- ``MXTPU_FLIGHTREC_MAX_DUMPS`` (default 32): per-process dump cap, so
  a crash loop or a thread-death storm cannot fill the disk.

Ring entry wire format (internal): the per-op dispatch site appends a
bare ``str`` (the op name — timestamp interpolated at dump time); the
helper recorders and ``profiler.record_op`` append
``(ph, name, category, tid, ts_s, value, args)`` with ``ph`` one of
``"X"`` (span, value = dur_us, ts_s = span END in perf_counter
seconds), ``"C"`` (counter, value = number or series dict), ``"i"``
(marker). perf_counter seconds convert onto the profiler trace clock
only at dump time.
"""
from __future__ import annotations

import collections
import os
import signal
import sys
import threading
import time
import traceback
from ..base import getenv as _getenv

__all__ = [
    "ENABLED", "RING", "enable", "disable", "configure", "reset_ring",
    "record_span", "record_counter", "record_marker", "snapshot",
    "stats", "dump", "dump_dir", "set_context", "install", "uninstall",
    "last_dumps",
]


def _env_on(name, default="1"):
    return _getenv(name, default) not in ("0", "false", "off")


# Master switch, read inline (one attribute load) by profiler._LIVE and
# the shared-guard record sites. enable()/disable() keep the profiler's
# _LIVE mirror in sync.
ENABLED = _env_on("MXTPU_FLIGHTREC")

_CAP = max(16, int(_getenv("MXTPU_FLIGHTREC_EVENTS", "4096") or 4096))
_MAX_DUMPS = int(_getenv("MXTPU_FLIGHTREC_MAX_DUMPS", "32") or 32)

# The ring. deque(maxlen=) is a C ring buffer: append is O(1) and
# GIL-atomic, old entries fall off the far end — lock-light by
# construction. Hot sites append raw tuples directly (see module
# docstring for the entry format).
RING = collections.deque(maxlen=_CAP)  # mxlint: disable=MX003 (deque append/clear are GIL-atomic C ops; a lock here is exactly what the always-on budget forbids)

# mxlint: disable=MX003 (GIL-atomic best-effort counters off the per-op hot path: the raw hot-site append deliberately does NOT count — stats() derives what it can)
_STATS = {
    "recorded": 0,     # entries appended through the helper recorders
    "dumps": 0,        # shards written
    "dump_failures": 0,
}
_DUMP_PATHS = collections.deque(maxlen=16)  # newest shard paths  # mxlint: disable=MX003 (GIL-atomic deque append on the rare dump path)
_SEQ = [0]  # mxlint: disable=MX003 (GIL-atomic bump on the rare dump path; worst case two dumps share a suffix attempt and rename last-writer-wins)

_context = {}                   # set_context() payloads, bundled per dump
_context_lock = threading.Lock()

_prev_sys_hook = None
_prev_threading_hook = None
_prev_sigusr2 = None
_fatal_file = None
_installed = False


def _sync_profiler_live():
    """Refresh profiler._LIVE (the shared hot-path guard) after an
    ENABLED flip. Lazy import: profiler imports this module at load."""
    try:
        from .. import profiler
        profiler._update_live()
    except Exception:
        pass


def enable():
    """Turn the recorder on at runtime. Returns the previous state."""
    global ENABLED
    prev = ENABLED
    ENABLED = True
    _sync_profiler_live()
    return prev


def disable():
    global ENABLED
    prev = ENABLED
    ENABLED = False
    _sync_profiler_live()
    return prev


def configure(capacity=None, enabled=None):
    """Resize the ring (drops buffered entries) and/or flip the master
    switch — test/tooling surface; production uses the env knobs."""
    global RING, _CAP
    if capacity is not None:
        _CAP = max(16, int(capacity))
        RING = collections.deque(RING, maxlen=_CAP)
    if enabled is not None:
        (enable if enabled else disable)()


def reset_ring():
    """Drop every buffered entry and zero the counters (test isolation)."""
    RING.clear()
    for k in _STATS:
        _STATS[k] = 0
    _DUMP_PATHS.clear()


def dump_dir():
    """Where shards (and the faulthandler fatal file) land:
    ``MXTPU_FLIGHTREC_DIR`` or ``./flightrec`` — created lazily by
    :func:`_ensure_dump_dir` at the first actual write, so importing the
    framework never litters the CWD."""
    return _getenv("MXTPU_FLIGHTREC_DIR", "") or \
        os.path.join(os.getcwd(), "flightrec")


def _ensure_dump_dir():
    d = dump_dir()
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        pass  # read-only CWD: the write itself will surface the error
    return d


def set_context(key, value):
    """Attach a JSON-safe blob to every future dump under
    ``metadata.context[key]`` — e.g. the elastic controller publishes
    its committed world/dead-rank view here so a post-mortem names the
    job topology at the instant of death."""
    with _context_lock:
        _context[key] = value


# -- recording ---------------------------------------------------------------
# Helper recorders for everything OFF the per-op dispatch path (the
# profiler primitives route through these). The per-op dispatch site in
# ndarray/register.py appends a bare op name inline instead — the
# helper-call overhead (or even one clock read) alone would breach the
# <0.5%-of-dispatch budget.

def record_span(name, dur_us, category="operator", tid=0, args=None):
    RING.append(("X", name, category, tid, time.perf_counter(), dur_us,
                 args))
    _STATS["recorded"] += 1


def record_counter(name, value, tid=0, args=None):
    RING.append(("C", name, "counter", tid, time.perf_counter(), value,
                 args))
    _STATS["recorded"] += 1


def record_marker(name, category="instant", tid=0, args=None):
    RING.append(("i", name, category, tid, time.perf_counter(), 0, args))
    _STATS["recorded"] += 1


def snapshot():
    """Atomic copy of the ring, oldest first (list(deque) runs as one C
    call under the GIL — no torn reads, no lock)."""
    return list(RING)


def stats():
    """Flat JSON-safe counters — ``profiler.metrics()['flightrec']``
    (registered as a stats provider by the profiler). ``recorded``
    counts helper-recorded entries only; the raw per-op appends are
    deliberately uncounted (the budget forbids a counter bump there),
    so ``buffered`` is the ground truth for ring occupancy."""
    return {
        "enabled": bool(ENABLED),
        "capacity": _CAP,
        "buffered": len(RING),
        "recorded": _STATS["recorded"],
        "dumps": _STATS["dumps"],
        "dump_failures": _STATS["dump_failures"],
    }


def last_dumps():
    """Paths of the most recent shards this process wrote."""
    return list(_DUMP_PATHS)


# -- dumping -----------------------------------------------------------------

def _thread_stacks():
    """{thread name (id): [frame lines]} for every live thread — the
    'where was everyone' half of a post-mortem."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = "%s (%d)" % (names.get(tid, "?"), tid)
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out


def _render_events(entries, profiler):
    """Ring entries -> chrome-trace events on the profiler's trace clock
    and pid/lanes, so a flight-record shard merges with live profiler
    shards into one aligned timeline.

    Bare-name breadcrumbs (the clock-free per-op dispatch records) have
    no timestamp of their own: each renders as an instant event at the
    most recent timestamped entry's time (leading ones backfill from
    the first anchor; a ring with no anchors at all falls back to dump
    time), flagged ``args.ts_approx`` — the *order* is exact, the time
    is bounded by the neighboring anchors."""
    t0 = profiler._t0
    pid = profiler.PID
    events = []
    pending = []     # leading bare-name entries awaiting the 1st anchor
    last_ts = None   # newest anchor, trace-clock us

    def _bare(name, ts):
        return {"name": name, "cat": "operator", "ph": "i", "s": "t",
                "ts": ts, "pid": pid, "tid": 1,  # imperative lane
                "args": {"ts_approx": True}}

    for e in entries:
        if isinstance(e, str):  # bare-name dispatch breadcrumb
            if last_ts is None:
                pending.append(e)
            else:
                events.append(_bare(e, last_ts))
            continue
        ph, name, cat, tid, ts_s, value, args = e
        ev = {"name": name, "cat": cat, "ph": ph,
              "ts": (ts_s - t0) * 1e6, "pid": pid, "tid": tid}
        if ph == "X":
            ev["ts"] -= value  # helper records at span END
            ev["dur"] = value
        elif ph == "C":
            ev["args"] = (dict(value) if isinstance(value, dict)
                          else {"value": value})
        elif ph == "i":
            ev["s"] = "p"
        if args:
            a = dict(ev.get("args", ()))
            a.update(args)
            ev["args"] = a
        last_ts = (ts_s - t0) * 1e6
        if pending:
            events.extend(_bare(n, ev["ts"]) for n in pending)
            del pending[:]
        events.append(ev)
    if pending:  # no timestamped entry in the whole ring
        now = (time.perf_counter() - t0) * 1e6
        events.extend(_bare(n, now) for n in pending)
    return events


def dump(trigger, extra=None, path=None, swallow=False):
    """Write one flight-recorder shard (chrome-trace JSON, atomic
    temp+rename via ``base.atomic_write``) and return its path.

    ``trigger`` names why (``exception`` / ``thread-exception`` /
    ``sigusr2`` / ``watchdog`` / ``manual``); ``extra`` lands under
    ``metadata.trigger_info``. With ``swallow=True`` (the hook paths —
    a failing dump must never mask the original crash) failures are
    counted and ``None`` is returned instead of raising."""
    try:
        return _dump(trigger, extra, path)
    except Exception:
        _STATS["dump_failures"] += 1
        if swallow:
            return None
        raise


def _dump(trigger, extra, path):
    if _STATS["dumps"] >= _MAX_DUMPS and path is None:
        return None  # dump-storm cap: a crash loop must not fill the disk
    import json

    from .. import base, profiler
    from . import faultpoint

    entries = snapshot()
    events = profiler._lane_metadata() + _render_events(entries, profiler)
    events.append({"name": "flightrec:%s" % trigger, "cat": "flightrec",
                   "ph": "i", "s": "g", "ts": profiler._now_us(),
                   "pid": profiler.PID,
                   "tid": profiler.LANES["user"]})
    try:
        metrics = profiler.metrics()
    except Exception as e:  # the crashing process may be half-torn-down
        metrics = {"error": "%s: %s" % (type(e).__name__, e)}
    with _context_lock:
        context = dict(_context)
    # the run-level goodput partition (ISSUE 14): a post-mortem names
    # not just the instant of death but what the whole run's wall-clock
    # had bought up to it. Lazy import — goodput bottom-imports the
    # profiler, which imports this module at load.
    try:
        from . import goodput
        goodput_block = goodput.snapshot()
    except Exception:
        goodput_block = None
    # the roofline/MFU join (ISSUE 17): a post-mortem names which
    # signature was binding on what when the run died. Same lazy-import
    # discipline as the goodput block.
    try:
        from . import perfmodel
        perf_block = perfmodel.snapshot()
    except Exception:
        perf_block = None
    data = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "rank": profiler.PID,
            "flightrec": True,
            "trigger": trigger,
            "trigger_info": extra or {},
            "clock_sync": profiler.clock_sync(),
            "python_stacks": _thread_stacks(),
            "metrics": metrics,
            "faults": faultpoint.metrics(),
            "goodput": goodput_block,
            "perf": perf_block,
            "context": context,
            "ring": {"buffered": len(entries), "capacity": _CAP},
        },
    }
    if path is None:
        _SEQ[0] += 1
        path = os.path.join(
            _ensure_dump_dir(), "flightrec_r%d_%s_%03d.json"
            % (profiler.PID, trigger, _SEQ[0]))
    with base.atomic_write(path, "w") as f:
        json.dump(data, f, default=str)
    _STATS["dumps"] += 1
    _DUMP_PATHS.append(path)
    return path


# -- crash hooks -------------------------------------------------------------

def _sys_excepthook(exc_type, exc, tb):
    # an unhandled XLA RESOURCE_EXHAUSTED is the OOM post-mortem seam
    # (ISSUE 13): upgrade the trigger so the shard names its cause and
    # carries the allocation ledger's view of what was resident
    trigger = "exception"
    extra = {"exception": "%s: %s" % (exc_type.__name__, exc)}
    try:
        from . import memwatch
        if memwatch.is_oom(exc):
            if memwatch.was_reported(exc):
                trigger = None  # a handled-then-reraised OOM: one shard
            else:
                trigger = "oom"
                from .. import storage
                ledger = storage.ledger_metrics()
                extra["ledger_total_bytes"] = ledger.get("total_bytes")
                extra["ledger_by_tag"] = ledger.get("by_tag", {})
                extra["top_sites"] = ledger.get("top_sites", [])
    except Exception:
        pass
    if trigger is not None:
        dump(trigger, extra=extra, swallow=True)
    if _prev_sys_hook is not None:
        _prev_sys_hook(exc_type, exc, tb)


def _threading_excepthook(args):
    if args.exc_type is SystemExit:
        pass  # thread called sys.exit: not a crash
    else:
        dump("thread-exception",
             extra={"thread": getattr(args.thread, "name", "?"),
                    "exception": "%s: %s" % (args.exc_type.__name__,
                                             args.exc_value)},
             swallow=True)
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


_sigusr2_inflight = threading.Lock()


def _sigusr2_dump_thread():
    try:
        # reads state only — a mid-training dump is loss- and bitwise-
        # neutral (tests/test_flightrec.py pins that)
        dump("sigusr2", swallow=True)
    finally:
        _sigusr2_inflight.release()


def _sigusr2_handler(signum, frame):
    # NEVER dump inline: the handler preempts the main thread between
    # bytecodes, and dump() takes profiler/watchdog/context locks — all
    # non-reentrant. If the signal lands inside one of their ``with
    # _lock:`` regions (e.g. account() on a kvstore byte ledger), an
    # inline dump deadlocks the main thread on its own lock. A helper
    # thread merely blocks until the main thread resumes and releases.
    if _sigusr2_inflight.acquire(blocking=False):
        threading.Thread(target=_sigusr2_dump_thread,
                         name="flightrec-sigusr2", daemon=True).start()
    if callable(_prev_sigusr2):
        _prev_sigusr2(signum, frame)


def install():
    """Wire the dump triggers (idempotent): chain ``sys.excepthook`` and
    ``threading.excepthook``, take SIGUSR2 (main thread only; chains to
    any user handler), and enable ``faulthandler`` into a sibling
    ``flightrec_r<rank>_fatal.txt`` unless something (e.g. pytest)
    already owns it. Called at import by the profiler when the recorder
    is enabled."""
    global _prev_sys_hook, _prev_threading_hook, _prev_sigusr2
    global _fatal_file, _installed
    if _installed:
        return
    _installed = True
    _prev_sys_hook = sys.excepthook
    sys.excepthook = _sys_excepthook
    _prev_threading_hook = threading.excepthook
    threading.excepthook = _threading_excepthook
    try:
        _prev_sigusr2 = signal.signal(signal.SIGUSR2, _sigusr2_handler)
    except (ValueError, OSError, AttributeError):
        _prev_sigusr2 = None  # non-main thread / platform without USR2
    try:
        import faulthandler
        if not faulthandler.is_enabled():
            fatal_path = os.path.join(
                _ensure_dump_dir(), "flightrec_r%d_fatal.txt"
                % int(_getenv("MXTPU_PROC_ID", "0") or 0))
            # append, never truncate: an elastic restart in the same
            # dump dir (same MXTPU_PROC_ID) must not erase the PREVIOUS
            # incarnation's native stacks — the one artifact a SIGSEGV
            # leaves behind. The clean-exit cleanup only removes the
            # file when it is empty, so preserved content survives.
            _fatal_file = open(fatal_path, "a")
            faulthandler.enable(file=_fatal_file)
            import atexit
            atexit.register(_cleanup_fatal_file, fatal_path)
    except Exception:
        _fatal_file = None  # a read-only cwd must not break import


def _cleanup_fatal_file(path):
    """A clean exit leaves no litter: the faulthandler file only stays
    behind when a fatal signal actually wrote native stacks into it."""
    global _fatal_file
    f, _fatal_file = _fatal_file, None
    if f is None:
        return
    try:
        import faulthandler
        if faulthandler.is_enabled():
            faulthandler.disable()
        f.close()
        if os.path.getsize(path) == 0:
            os.remove(path)
            # only the lazily-created DEFAULT dir is cleaned up on a
            # clean exit; an operator-configured MXTPU_FLIGHTREC_DIR
            # (pre-created, owned, permissioned) is never touched
            if not _getenv("MXTPU_FLIGHTREC_DIR", ""):
                try:
                    os.rmdir(os.path.dirname(path))
                except OSError:
                    pass
    except Exception:
        pass


def uninstall():
    """Undo install() (test isolation)."""
    global _prev_sys_hook, _prev_threading_hook, _prev_sigusr2
    global _fatal_file, _installed
    if not _installed:
        return
    _installed = False
    if sys.excepthook is _sys_excepthook:
        sys.excepthook = _prev_sys_hook or sys.__excepthook__
    if threading.excepthook is _threading_excepthook and \
            _prev_threading_hook is not None:
        threading.excepthook = _prev_threading_hook
    if _prev_sigusr2 is not None:
        try:
            if signal.getsignal(signal.SIGUSR2) is _sigusr2_handler:
                signal.signal(signal.SIGUSR2, _prev_sigusr2)
        except (ValueError, OSError):
            pass
    _prev_sys_hook = _prev_threading_hook = _prev_sigusr2 = None
    if _fatal_file is not None:
        try:
            import faulthandler
            if faulthandler.is_enabled():
                faulthandler.disable()
            _fatal_file.close()
        except Exception:
            pass
        _fatal_file = None


if ENABLED and _env_on("MXTPU_FLIGHTREC_HOOKS"):
    install()
