"""Run-level goodput ledger: wall-clock badput attribution per run.

Every prior observability layer explains an *instant* of a run — trace
lanes (PR 2/6), the flight recorder and stall watchdog (PR 8), the HBM
ledger (PR 12). Nothing accounts for the *whole run*: after an elastic
chaos run with a rank death, nobody can say what fraction of wall-clock
was productive training vs compile vs input-wait vs
recovery-and-rewind. That per-run efficiency breakdown is the top-line
SLO production fleets watch (the MegaScale-style goodput ratio;
tf.data-service-style input-bound attribution) — and the measurement
layer every scale-out claim in ROADMAP items 2/3/5 needs.

This module classifies every second between ``open_run()`` and
``close_run()`` into exactly one of :data:`CATEGORIES`:

==================  =========================================================
``compute``         steady-state training steps (watchdog beacon,
                    non-warmup, not replayed)
``compile``         the warm-up ramp: jit-compile steps and the
                    eager-warming steps before a signature compiles
``input_wait``      consumer stalls waiting on the input pipeline (the
                    ``io.prefetch_wait`` series' sites: DevicePrefetchIter,
                    PrefetchingIter, DecodePool)
``checkpoint``      ``CheckpointManager`` save/restore time outside
                    recovery intervals
``recovery``        restore + reshard intervals (``elastic_train_loop``
                    rewinding to a checkpoint, live resharding after a
                    rank death, resuming a preempted incarnation)
``rewind_replay``   steps re-executed after a restore — work the run had
                    already done once: pure badput
``host_overhead``   steady-state eager-fallback steps plus the
                    between-step residual no other category explains
``idle``            wall-clock outside the stepping window (setup,
                    teardown) not explained by recovery/checkpoint
==================  =========================================================

Price engineering (the drain-time discipline of the PR 12 memory
ledger): the hot path gains **no new clock reads and takes no lock**.
Every signal is a value the stack already computes under the existing
shared telemetry guard — the watchdog step beacon's ``dur`` (one
``note_step`` per *step*, not per op, called after the watchdog
releases its lock), the prefetch consumers' ``wait_us`` (computed
inside the existing ``t0 is not None`` block), and the rare
checkpoint/recovery paths' own timing. The hot sites are ONE
GIL-atomic ``deque.append`` each (a tuple for steps, a bare float for
input waits); ALL classification/bookkeeping folds into the run
accumulator at DRAIN time under one named lock, on whoever asks — the
watchdog poller each pass, ``metrics()``, ``close_run()`` — with a
size backstop so an undrained run stays bounded
(``BENCH_MODEL=goodput_overhead`` prices the hot shapes at <0.1% of a
fused step).

Partition math (drain): step-beacon seconds (compute + compile +
rewind_replay + fallback host time) are disjoint intervals inside the
stepping window ``[first step begin, last step end]``. input_wait /
checkpoint / recovery seconds fall between steps. The gap inside the
stepping window not explained by those is ``host_overhead``; wall-clock
outside the window not explained by their overflow is ``idle`` — the
eight categories always sum to the run's wall-clock exactly.

Each closed run publishes an atomic temp+rename manifest
``$MXTPU_RUNS_DIR/<run_id>/manifest.json`` (schema
``mxtpu.goodput.run/1``: env snapshot incl. the compile-signature
token values, per-category seconds, goodput ratio, step-time summary,
elastic/fault event annotations). ``tools/goodput_report.py`` renders
one manifest and ``--compare A B`` gives a noise-robust cross-run
regression verdict — the machine-checkable perf trajectory across runs
and bench rounds (``bench.py`` writes every BENCH_MODEL gate result in
the same schema).

Live surfaces: ``profiler.metrics()['goodput']`` (registered provider),
a Goodput block in ``profiler.dumps()``, ``mxtpu_goodput_*`` gauges on
``/metrics``, and a goodput block in every flight-record dump.

Env knobs (docs/ENV_VARS.md): ``MXTPU_GOODPUT`` (default 1),
``MXTPU_RUNS_DIR`` (default ``./runs``, created lazily at the first
manifest write), ``MXTPU_GOODPUT_EVENTS`` (default 64).
"""
from __future__ import annotations

import collections
import json
import math
import os
import time

from . import locktrace as _locktrace
from ..base import getenv as _getenv

__all__ = [
    "ENABLED", "OPEN", "CATEGORIES", "SCHEMA",
    "open_run", "close_run", "is_open", "current_run_id",
    "note_step", "note_input_wait", "note_checkpoint", "note_event",
    "recovery_begin", "recovery_end", "mark_replay", "fold_pending",
    "snapshot", "last_manifest", "runs_dir", "manifest_path",
    "load_manifest", "write_bench_manifest", "reset",
]

ENABLED = _getenv("MXTPU_GOODPUT", "1") not in ("0", "false", "off")

# the fixed taxonomy — every manifest carries all eight, summing to wall
CATEGORIES = ("compute", "compile", "input_wait", "checkpoint",
              "recovery", "rewind_replay", "host_overhead", "idle")

SCHEMA = "mxtpu.goodput.run/1"

_MAX_EVENTS = max(0, int(_getenv("MXTPU_GOODPUT_EVENTS", "64") or 64))

_lock = _locktrace.named_lock("goodput.run")

# Inline fast flag for the welds (watchdog beacon, prefetch consumers):
# one module-attribute truth test when no run is open — the same shape
# as faultpoint.ACTIVE. Maintained strictly under _lock with _run.
OPEN = False

_run = None    # open-run accumulator dict (all mutation under _lock)
_last = None   # manifest dict of the most recently closed run

# The hot-path mailboxes (the PR 12 ledger idiom): deque.append is a
# GIL-atomic C op — no lock, no clock read on the step/batch path.
# _PENDING carries (begin_m, dur_s, warmup, mode) step tuples plus
# _REPLAY_MARK order markers; _WAITS carries bare wait_us floats.
# Folded into _run at drain time under _lock; cleared at open_run so a
# stray post-close append can never leak into the next run.
_PENDING = collections.deque()  # mxlint: disable=MX003 (GIL-atomic deque appends on the per-step hot path; all multi-field bookkeeping folds under _lock at drain — the memory-ledger idiom)
_WAITS = collections.deque()    # mxlint: disable=MX003 (GIL-atomic deque appends on the per-batch hot path; folded under _lock at drain)
_REPLAY_MARK = ("replay",)
# backstop only: the watchdog poller (and every metrics() snapshot)
# folds far more often — this bound just keeps a never-scraped run's
# memory finite (~10 MB of tuples worst case)
_FOLD_AT = 1 << 17


def runs_dir():
    """Where run manifests land: ``MXTPU_RUNS_DIR`` or ``./runs`` —
    created lazily at the first manifest write, so importing the
    framework (or a run that never closes) litters nothing."""
    return _getenv("MXTPU_RUNS_DIR", "") or \
        os.path.join(os.getcwd(), "runs")


def manifest_path(run_id):
    return os.path.join(runs_dir(), str(run_id), "manifest.json")


def is_open():
    return _run is not None


def current_run_id():
    r = _run
    return r["run_id"] if r is not None else None


_RUN_SEQ = [0]  # mxlint: disable=MX003 (bumped only under _lock in open_run)


def _default_run_id():
    # wall-clock is metadata here (a human-sortable id), never trace
    # math; collisions are broken by rank, pid, AND a per-process
    # sequence — two sub-second back-to-back loops in one process must
    # not silently overwrite each other's manifest
    lt = time.localtime()
    _RUN_SEQ[0] += 1
    return "run_%04d%02d%02d_%02d%02d%02d_r%s_p%d_%03d" % (
        lt.tm_year, lt.tm_mon, lt.tm_mday, lt.tm_hour, lt.tm_min,
        lt.tm_sec, _getenv("MXTPU_PROC_ID", "0") or "0", os.getpid(),
        _RUN_SEQ[0])


def _env_snapshot(meta):
    """The reproducibility half of the manifest: who ran, on what
    topology, with which compile-signature token values — enough to
    judge whether two runs are comparable at all."""
    env = {
        "rank": int(_getenv("MXTPU_PROC_ID", "0") or 0),
        "world": meta.get("world"),
        "mesh": meta.get("mesh"),
    }
    try:
        from ..ndarray import register as _register
        env["signature_tokens"] = dict(
            zip(_register.signature_token_names(),
                _register.signature_tokens()))
    except Exception:
        env["signature_tokens"] = {}
    return env


def open_run(run_id=None, meta=None):
    """Open the process's run ledger; returns the run id (``None`` when
    disabled or a run is already open — nested loops do not reopen).
    ``meta`` is a JSON-safe dict stored in the manifest (world/mesh
    topology keys feed the env snapshot)."""
    global OPEN, _run
    if not ENABLED:
        return None
    meta = dict(meta or {})
    with _lock:
        if _run is not None:
            return None
        # stray appends from after the previous close must not leak in
        _PENDING.clear()
        _WAITS.clear()
        _run = {
            "run_id": str(run_id) if run_id else _default_run_id(),
            # mxlint: disable=MX007 (wall-clock METADATA for the manifest timestamps; all interval math below uses monotonic)
            "opened_unix": time.time(),
            "t0": time.monotonic(),
            "meta": meta,
            "env": _env_snapshot(meta),
            "cat": {c: 0.0 for c in CATEGORIES},
            "stepped_s": 0.0,      # all beacon step seconds (in-window)
            "first_begin": None,   # monotonic begin of the first step
            "last_end": None,      # monotonic end of the last step
            "steps": 0, "warmup_steps": 0, "replayed_steps": 0,
            "fallback_steps": 0,
            "step_sum_s": 0.0, "step_min_s": math.inf,
            "step_max_s": 0.0,
            "buckets": {},         # log-bucket histogram of step seconds
            "sigs": {},            # per-compile-signature step stats
            "sigs_dropped": 0,
            "replay_next": False,
            "in_recovery": False, "rec_t0": None,
            "recoveries": 0, "reshards": 0, "checkpoints": 0,
            "restores": 0, "persists": 0, "persist_s": 0.0,
            "peer_restores": 0,
            "events": [], "events_dropped": 0,
        }
        OPEN = True
        run = _run["run_id"]
    return run


def _event_locked(r, kind, detail):
    if len(r["events"]) >= _MAX_EVENTS:
        r["events_dropped"] += 1
        return
    ev = {"t_s": round(time.monotonic() - r["t0"], 6), "kind": kind}
    if detail:
        ev.update(detail)
    r["events"].append(ev)


def note_event(kind, **detail):
    """Annotate the open run (elastic/fault events land here: rank
    deaths, reshards, step failures). Bounded by
    ``MXTPU_GOODPUT_EVENTS``; overflow is counted, never unbounded."""
    if not OPEN:
        return
    with _lock:
        if _run is not None:
            _event_locked(_run, kind, detail)


def note_step(begin_m, dur_s, warmup=False, mode=None, sig=None):
    """One completed outer training step (the watchdog beacon feed).
    ``begin_m`` is the beacon's monotonic start, ``dur_s`` the duration
    it already computed: no new clock reads, no lock — one GIL-atomic
    append; classification happens at drain
    (:func:`_fold_step_locked`). ``sig`` is the fused step's
    compile-signature tag (ISSUE 17): one extra tuple field, so the
    manifest can carry per-signature step-time stats for the roofline
    join."""
    if not OPEN:
        return
    _PENDING.append((begin_m, dur_s, warmup, mode, sig))
    if len(_PENDING) >= _FOLD_AT:
        fold_pending()  # backstop: a never-drained run stays bounded


def mark_replay():
    """Tag the NEXT completed step as a rewind replay —
    ``elastic_train_loop`` calls this right before re-executing a step
    index it had already completed before a restore. An order marker in
    the same mailbox keeps the pairing exact across folds."""
    if not OPEN:
        return
    _PENDING.append(_REPLAY_MARK)


def note_input_wait(wait_us):
    """One consumer stall waiting on the input pipeline — fed by the
    ``io.prefetch_wait`` sites from the ``wait_us`` they already
    measured under the shared telemetry guard. One GIL-atomic float
    append; summed at drain."""
    if not OPEN:
        return
    _WAITS.append(wait_us)
    if len(_WAITS) >= _FOLD_AT:
        fold_pending()


_MAX_SIGS = 64  # per-run signature stats cap (hot sigs are few)


def _fold_step_locked(r, begin_m, dur_s, warmup, mode, sig=None):
    """Classify one step entry into the accumulator (caller holds
    ``_lock``): a replay-marked step is ``rewind_replay`` (work the run
    already did once); warm-up completions are ``compile`` (jit-compile
    + eager-warming ramp) except steady-state ``fallback:*`` modes,
    which are host-bound execution (``host_overhead``); everything else
    is ``compute``. A signature-tagged representative step additionally
    feeds that signature's own stats (the manifest's measured half of
    the ISSUE 17 roofline join)."""
    end = begin_m + dur_s
    if r["first_begin"] is None or begin_m < r["first_begin"]:
        r["first_begin"] = begin_m
    if r["last_end"] is None or end > r["last_end"]:
        r["last_end"] = end
    r["stepped_s"] += dur_s
    replay = r["replay_next"]
    r["replay_next"] = False
    if replay:
        r["cat"]["rewind_replay"] += dur_s
        r["replayed_steps"] += 1
    elif warmup:
        if mode is not None and mode.startswith("fallback"):
            r["cat"]["host_overhead"] += dur_s
            r["fallback_steps"] += 1
        else:
            r["cat"]["compile"] += dur_s
        r["warmup_steps"] += 1
    else:
        r["cat"]["compute"] += dur_s
    if not warmup:
        # representative step times. Steady-state replays run the same
        # program and count; a replayed step the beacon flagged warmup
        # (e.g. the recompile a post-reshard rewind forces under the
        # new mesh) stays OUT — a seconds-long compile in the p95/max
        # would hand the compare CLI a false cross-run regression
        r["steps"] += 1
        r["step_sum_s"] += dur_s
        r["step_min_s"] = min(r["step_min_s"], dur_s)
        r["step_max_s"] = max(r["step_max_s"], dur_s)
        idx = _bucket_index(dur_s * 1e6)
        r["buckets"][idx] = r["buckets"].get(idx, 0) + 1
        if sig is not None and not replay:
            s = r["sigs"].get(sig)
            if s is None:
                if len(r["sigs"]) >= _MAX_SIGS:
                    r["sigs_dropped"] += 1
                    return
                s = r["sigs"][sig] = {
                    "count": 0, "sum_s": 0.0, "min_s": math.inf,
                    "max_s": 0.0, "buckets": {}}
            s["count"] += 1
            s["sum_s"] += dur_s
            s["min_s"] = min(s["min_s"], dur_s)
            s["max_s"] = max(s["max_s"], dur_s)
            s["buckets"][idx] = s["buckets"].get(idx, 0) + 1


def _fold_locked(r):
    """Drain both mailboxes into the accumulator (caller holds
    ``_lock``). popleft races benignly with concurrent appends: an
    entry lands in either this fold or the next."""
    while _WAITS:
        r["cat"]["input_wait"] += _WAITS.popleft() / 1e6
    while _PENDING:
        e = _PENDING.popleft()
        if e is _REPLAY_MARK:
            r["replay_next"] = True
        else:
            _fold_step_locked(r, *e)


def fold_pending():
    """Fold the hot-path mailboxes into the run accumulator — called by
    the watchdog poller each pass, every snapshot/close, and the
    hot-path size backstop. No-op when no run is open (post-close
    strays are discarded at the next ``open_run``)."""
    with _lock:
        if _run is not None:
            _fold_locked(_run)


def note_checkpoint(dur_s, kind="save"):
    """Checkpoint save/restore wall time (``CheckpointManager`` weld).
    A restore inside a recovery interval is already covered by that
    interval's clock — only the counter ticks, not the category.

    ``kind="persist"`` (ISSUE 19 async checkpoints) is the background
    publish leg: its seconds OVERLAP training on the persist thread, so
    they never book into the ``checkpoint`` category — only the counter
    and an overlap gauge (``persist_s``) tick, which is exactly how the
    async path's badput win shows up in a manifest: ``checkpoint``
    seconds shrink to the blocking snapshot while ``persist_s`` records
    the hidden work."""
    if not OPEN:
        return
    with _lock:
        r = _run
        if r is None:
            return
        if kind == "save":
            r["checkpoints"] += 1
        elif kind == "persist":
            r["persists"] += 1
            r["persist_s"] += dur_s
            return
        else:
            r["restores"] += 1
        if not r["in_recovery"]:
            r["cat"]["checkpoint"] += dur_s


def recovery_begin():
    """Open a recovery interval (restore + reshard). Re-entrant safe:
    an already-open interval is left alone (the outer one owns the
    clock)."""
    if not OPEN:
        return
    with _lock:
        r = _run
        if r is None or r["in_recovery"]:
            return
        r["in_recovery"] = True
        r["rec_t0"] = time.monotonic()


def recovery_end(kind="restore", resharded=False, restored_step=None,
                 replay_span=0, ok=True, count=True):
    """Close the recovery interval opened by :func:`recovery_begin`:
    its wall time lands in ``recovery`` (unless ``count=False`` — e.g.
    a loop-start probe that found nothing to restore) and an event
    annotation records what happened."""
    if not OPEN:
        return
    with _lock:
        r = _run
        if r is None or not r["in_recovery"]:
            return
        dur = time.monotonic() - r["rec_t0"]
        r["in_recovery"] = False
        r["rec_t0"] = None
        if not count:
            return
        r["cat"]["recovery"] += dur
        r["recoveries"] += 1
        if resharded:
            r["reshards"] += 1
        if kind == "peer":
            # restore served from a live peer's in-memory replica
            # (ISSUE 19c) instead of the filesystem
            r["peer_restores"] += 1
        _event_locked(r, "recovery", {
            "recovery_kind": kind, "seconds": round(dur, 6),
            "resharded": bool(resharded),
            "restored_step": restored_step,
            "replay_span": int(replay_span), "ok": bool(ok)})


# -- drain -------------------------------------------------------------------

def _bucket_index(dur_us):
    """The profiler's own log-bucket packing (lazy import, the
    ``_percentile`` pattern): ONE copy of the (exponent, sub-bucket)
    math, so the step-time percentiles stay exactly comparable with
    the latency histograms."""
    from .. import profiler as _profiler
    return _profiler._bucket_index(dur_us)


def _percentile(buckets, count, q):
    from .. import profiler as _profiler
    return _profiler._hist_percentile(buckets, count, q) / 1e6


def _derive_locked(r, now_m, closing):
    """The partition: category seconds summing exactly to wall-clock.
    Pure arithmetic over the accumulators — no other subsystem locks
    are touched (drain-time discipline, ISSUE 13's idiom)."""
    wall = max(0.0, now_m - r["t0"])
    cat = dict(r["cat"])
    if r["first_begin"] is not None:
        window = max(0.0, r["last_end"] - r["first_begin"])
    else:
        window = 0.0
    in_window = min(r["stepped_s"], window)
    gap_in_window = max(0.0, window - in_window)
    out_window = max(0.0, wall - window)
    # input_wait is the one category fed from threads that can run
    # CONCURRENTLY with steps (a stacked consumer's inner iterator on
    # a producer thread measures the same stall twice): wait seconds
    # beyond the run's total non-step budget are attribution noise,
    # trimmed here so the eight categories keep partitioning wall
    # exactly — the trimmed amount is surfaced, never silently dropped
    other = cat["input_wait"] + cat["checkpoint"] + cat["recovery"]
    overbooked = min(cat["input_wait"],
                     max(0.0, other - gap_in_window - out_window))
    if overbooked > 0.0:
        cat["input_wait"] -= overbooked
        other -= overbooked
    r["input_wait_overbooked_s"] = overbooked
    other_in_window = min(other, gap_in_window)
    cat["host_overhead"] += gap_in_window - other_in_window
    cat["idle"] = max(0.0, wall - window - (other - other_in_window))
    ratio = (cat["compute"] / wall) if wall > 0 else 0.0
    steps = {
        "count": r["steps"],
        "warmup": r["warmup_steps"],
        "replayed": r["replayed_steps"],
        "fallback": r["fallback_steps"],
    }
    if r["steps"]:
        n = r["steps"]
        b = r["buckets"]
        steps["time_s"] = {
            "mean": r["step_sum_s"] / n,
            "min": r["step_min_s"],
            "max": r["step_max_s"],
            "p50": min(r["step_max_s"], _percentile(b, n, 0.50)),
            "p95": min(r["step_max_s"], _percentile(b, n, 0.95)),
            "p99": min(r["step_max_s"], _percentile(b, n, 0.99)),
        }
    if r["sigs"]:
        steps["signatures"] = {
            sig: {
                "count": s["count"],
                "mean_s": s["sum_s"] / s["count"],
                "min_s": s["min_s"],
                "max_s": s["max_s"],
                "p50_s": min(s["max_s"], _percentile(
                    s["buckets"], s["count"], 0.50)),
            } for sig, s in r["sigs"].items()}
        if r["sigs_dropped"]:
            steps["signatures_dropped"] = r["sigs_dropped"]
    return {
        "schema": SCHEMA,
        "run_id": r["run_id"],
        "rank": r["env"].get("rank", 0),
        "opened_unix": r["opened_unix"],
        "wall_s": wall,
        "open": not closing,
        "categories_s": {c: cat[c] for c in CATEGORIES},
        "goodput_ratio": ratio,
        "steps": steps,
        "counters": {
            "recoveries": r["recoveries"],
            "reshards": r["reshards"],
            "checkpoint_saves": r["checkpoints"],
            "checkpoint_restores": r["restores"],
            "checkpoint_persists": r["persists"],
            "checkpoint_persist_s": round(r["persist_s"], 6),
            "peer_restores": r["peer_restores"],
            "events_dropped": r["events_dropped"],
            "input_wait_overbooked_s": round(
                r.get("input_wait_overbooked_s", 0.0), 6),
        },
        "env": r["env"],
        "events": list(r["events"]),
        "meta": dict(r["meta"]),
    }


def close_run(outcome="completed"):
    """Drain the open run into its manifest, publish it atomically
    under ``runs_dir()/<run_id>/manifest.json``, and return the
    manifest dict (``None`` when no run was open). A failed write never
    masks the caller's own exit path: the error lands in the returned
    manifest as ``write_error``."""
    global OPEN, _run, _last
    with _lock:
        r = _run
        if r is None:
            return None
        _fold_locked(r)
        manifest = _derive_locked(r, time.monotonic(), closing=True)
        _run = None
        OPEN = False
    _attach_perf(manifest)
    manifest["outcome"] = str(outcome)
    # mxlint: disable=MX007 (wall-clock METADATA: the manifest's closed-at timestamp, never interval math)
    manifest["closed_unix"] = time.time()
    try:
        _write_manifest(manifest)
        manifest["manifest_path"] = manifest_path(manifest["run_id"])
    except Exception as e:
        manifest["write_error"] = "%s: %s" % (type(e).__name__, e)
    with _lock:
        _last = manifest
    return manifest


def _attach_perf(manifest):
    """Attach the roofline join's ``perf`` block (ISSUE 17) — called
    OUTSIDE ``_lock`` (perfmodel owns its own named lock; drain-time
    lock discipline forbids nesting them). Lazy import: perfmodel
    bottom-imports the profiler like this module does."""
    try:
        from . import perfmodel
        blk = perfmodel.manifest_block()
    except Exception:
        blk = None
    if blk:
        manifest["perf"] = blk


def _write_manifest(manifest):
    from .. import base
    path = manifest_path(manifest["run_id"])
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with base.atomic_write(path, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True, default=str)


def load_manifest(path):
    """Read one manifest (a file path, or a run directory containing
    ``manifest.json``) and validate the schema tag."""
    if os.path.isdir(path):
        path = os.path.join(path, "manifest.json")
    with open(path, encoding="utf-8") as f:
        m = json.load(f)
    if m.get("schema") != SCHEMA:
        raise ValueError("%s: schema %r is not %r"
                         % (path, m.get("schema"), SCHEMA))
    return m


def last_manifest():
    """Manifest of the most recently closed run (this process)."""
    with _lock:
        return dict(_last) if _last is not None else None


def reset():
    """Discard any open run and the last manifest (test isolation)."""
    global OPEN, _run, _last
    with _lock:
        _run = None
        _last = None
        OPEN = False
        _PENDING.clear()
        _WAITS.clear()


# -- live snapshot (the metrics()['goodput'] provider) -----------------------

def snapshot():
    """Flat JSON-safe dict: the OPEN run's live partition, or the last
    closed run's totals. Cheap (pure arithmetic under one lock) and
    callable with profiling off — the stats-provider contract."""
    with _lock:
        if _run is not None:
            _fold_locked(_run)
            m = _derive_locked(_run, time.monotonic(), closing=False)
        elif _last is not None:
            m = _last
        else:
            return {"enabled": int(ENABLED), "open": 0}
    out = {"enabled": int(ENABLED), "open": int(bool(m.get("open"))),
           "run_id": m["run_id"], "wall_s": round(m["wall_s"], 6),
           "goodput_ratio": round(m["goodput_ratio"], 6),
           "steps": m["steps"]["count"],
           "warmup_steps": m["steps"]["warmup"],
           "replayed_steps": m["steps"]["replayed"],
           "recoveries": m["counters"]["recoveries"],
           "reshards": m["counters"]["reshards"]}
    for c in CATEGORIES:
        out["%s_s" % c] = round(m["categories_s"][c], 6)
    t = m["steps"].get("time_s")
    if t:
        out["step_p50_s"] = round(t["p50"], 6)
        out["step_mean_s"] = round(t["mean"], 6)
    if "outcome" in m:
        out["outcome"] = m["outcome"]
    return out


# -- bench manifests (the trajectory satellite) ------------------------------

# result keys a bench gate may carry, mapped to one representative
# step/op latency in seconds — the first match wins
_BENCH_STEP_KEYS = (
    ("median_step_s", 1.0),
    ("step_time_s", 1.0),
    ("fused_step_us", 1e-6),
    ("dispatch_us_per_op", 1e-6),
    ("p50_ms", 1e-3),
)
_BENCH_RATE_KEYS = ("steps_per_sec", "fused_steps_per_sec",
                    "imgs_per_sec", "samples_per_sec")


def _bench_step_seconds(result):
    for key, scale in _BENCH_STEP_KEYS:
        v = result.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return float(v) * scale
    for key in _BENCH_RATE_KEYS:
        v = result.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return 1.0 / float(v)
    if result.get("metric", "").endswith("_per_sec") and \
            isinstance(result.get("value"), (int, float)) \
            and result["value"] > 0:
        return 1.0 / float(result["value"])
    return None


def write_bench_manifest(model, result, run_id=None):
    """Publish one ``bench.py`` gate result as a goodput-run manifest
    (same schema), so ``tools/goodput_report.py --compare`` works
    across bench rounds — the standing bench-trajectory tool. Returns
    the manifest path (``None`` when goodput is disabled)."""
    if not ENABLED:
        return None
    step_s = _bench_step_seconds(dict(result))
    wall = float(result.get("wall_s", 0.0) or 0.0)
    compute = wall if wall > 0 else (step_s or 0.0)
    cats = {c: 0.0 for c in CATEGORIES}
    cats["compute"] = compute
    steps = {"count": 1 if step_s else 0, "warmup": 0, "replayed": 0,
             "fallback": 0}
    if step_s:
        steps["time_s"] = {"mean": step_s, "min": step_s,
                           "max": step_s, "p50": step_s, "p95": step_s,
                           "p99": step_s}
    gate = result.get("gate") if isinstance(result.get("gate"), dict) \
        else {}
    # mxlint: disable=MX007 (wall-clock METADATA: manifest timestamps + a sortable bench-round id, never interval math)
    now_unix = time.time()
    manifest = {
        "schema": SCHEMA,
        "run_id": str(run_id) if run_id else
        "bench_%s_%d" % (model, int(now_unix * 1000)),
        "rank": int(_getenv("MXTPU_PROC_ID", "0") or 0),
        "opened_unix": now_unix,
        "closed_unix": now_unix,
        "wall_s": max(wall, compute),
        "open": False,
        "outcome": "completed" if gate.get("ok", True) else
        "gate_breached",
        "categories_s": cats,
        "goodput_ratio": 1.0 if compute > 0 else 0.0,
        "steps": steps,
        "counters": {"recoveries": 0, "reshards": 0,
                     "checkpoint_saves": 0, "checkpoint_restores": 0,
                     "events_dropped": 0},
        "env": _env_snapshot({}),
        "events": [],
        "meta": {"bench_model": str(model)},
        "bench": {"model": str(model), "result": result},
    }
    _attach_perf(manifest)
    _write_manifest(manifest)
    return manifest_path(manifest["run_id"])


# registered at import, like the watchdog provider: every process that
# loads the telemetry stack carries metrics()['goodput']
from .. import profiler as _profiler  # noqa: E402,F401  (registration)

_profiler.register_stats_provider("goodput", snapshot)
