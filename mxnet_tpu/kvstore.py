"""KVStore: key-value parameter synchronization.

TPU-native re-design of the reference kvstore
(ref: include/mxnet/kvstore.h:59-438, src/kvstore/kvstore.cc:40-72 factory,
src/kvstore/comm.h CommCPU/CommDevice reduce, src/kvstore/kvstore_dist.h,
python/mxnet/kvstore.py:97).

Reference mechanism: per-GPU gradient copies are reduced over PCIe/NVLink
(local/device/nccl) or pushed to parameter-server shards over ZMQ (dist_*).
On TPU there are no per-device copies to reduce — a parameter is ONE logical
array (possibly sharded over the mesh), and cross-device reduction is an XLA
collective (`psum`/`reduce_scatter`) inserted by GSPMD inside the jitted
step (see mxnet_tpu.parallel). The KVStore API survives for user code:

- `local` / `device` / `nccl` / `tpu`: in-process store. push() sums the
  pushed values (the Comm reduce analog — a list of per-slice grads is
  summed on device in one fused XLA op), then either stores the result
  (update_on_kvstore=False) or applies the optimizer (set_optimizer was
  called, the server-side-update analog).
- `dist_sync` / `dist_device_sync`: multi-host variants. Under
  `jax.distributed` each process holds the same keys; push() additionally
  all-reduces across processes over ICI/DCN via
  `parallel.host_allreduce`.
- `dist_async`: host-driven asynchronous parameter server
  (kvstore_async.py) — a server thread in rank 0 applies each push
  immediately over a TCP transport, reproducing the reference's async
  staleness semantics (ICI collectives are inherently synchronous, so
  async cannot ride them — SURVEY §5).
"""
from __future__ import annotations

import os
import pickle
import time as _time
import warnings

from .ndarray import NDArray
from . import ndarray as nd
from . import optimizer as opt
from . import profiler as _profiler
from .base import getenv as _getenv

__all__ = ["KVStore", "create"]


def _ctype_key_value(keys, vals):
    """Normalize (key(s), val(s)) to parallel lists; keys are str or int
    (ref: python/mxnet/kvstore.py _ctype_key_value)."""
    if isinstance(keys, (str, int)):
        keys = [keys]
        vals = [vals]
    out_keys, out_vals = [], []
    for k, v in zip(keys, vals):
        if isinstance(v, (list, tuple)):
            out_keys.append(k)
            out_vals.append(list(v))
        else:
            out_keys.append(k)
            out_vals.append([v])
    return out_keys, out_vals


class KVStore:
    """In-process key-value store with the reference's full surface
    (ref: python/mxnet/kvstore.py:97)."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}            # key -> NDArray (the "server" weight)
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._compression_residuals = {}
        self._barrier_before_exit = True
        # wire accounting: what push/pull would cost on the network.
        # Row-sparse payloads count values+indices, not the dense shape
        # (ref: kvstore_dist.h:522 EncodeRowSparseKey ships only rows).
        self.bytes_pushed = 0
        self.bytes_pulled = 0

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        """ref: kvstore.py type."""
        return self._kind

    @property
    def rank(self):
        import jax
        return jax.process_index() if self._kind.startswith("dist") else 0

    @property
    def num_workers(self):
        import jax
        return jax.process_count() if self._kind.startswith("dist") else 1

    # -- init/push/pull ----------------------------------------------------
    def init(self, key, value):
        """Initialize a key with a value (ref: kvstore.py init)."""
        t0 = _time.perf_counter() if _profiler._LIVE else None
        keys, vals = _ctype_key_value(key, value)
        nbytes = 0
        for k, vlist in zip(keys, vals):
            if k in self._store:
                continue
            nbytes += int(vlist[0].nbytes)
            self._store[k] = NDArray(vlist[0]._data)
        if t0 is not None:
            _profiler.record_op(
                "kvstore.init", (_time.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": nbytes,
                      "type": self._kind})

    def push(self, key, value, priority=0):
        """Push values; multiple values per key are reduced (summed) exactly
        like Comm::Reduce (ref: src/kvstore/comm.h:451). With an optimizer
        set, the update is applied server-side (update_on_kvstore mode,
        ref: src/kvstore/kvstore_dist_server.h:346 ApplyUpdates)."""
        from .ndarray.sparse import RowSparseNDArray
        t0 = _time.perf_counter() if _profiler._LIVE else None
        b0 = self.bytes_pushed
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise ValueError("key %r has not been initialized" % (k,))
            for v in vlist:
                self.bytes_pushed += v.wire_nbytes \
                    if isinstance(v, RowSparseNDArray) else int(v.nbytes)
            merged = vlist[0] if len(vlist) == 1 else nd.add_n(*vlist)
            if self._compression_active(merged):
                merged = self._compress_reduce(k, merged)
            else:
                merged = self._sync_reduce(merged)
            if self._updater is not None:
                idx = k if isinstance(k, int) else _str_key_int(k)
                self._updater(idx, merged, self._store[k])
            else:
                self._store[k] = NDArray(merged._data)
        # accounted with profiling off too — metrics()['counters'] must
        # be trustworthy in production (account gates only trace output)
        _profiler.account("kvstore.bytes_pushed", self.bytes_pushed - b0)
        if t0 is not None:
            _profiler.record_op(
                "kvstore.push", (_time.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": self.bytes_pushed - b0,
                      "type": self._kind})

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Pull values into `out` (ref: kvstore.py pull)."""
        assert out is not None
        t0 = _time.perf_counter() if _profiler._LIVE else None
        b0 = self.bytes_pulled
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise ValueError("key %r has not been initialized" % (k,))
            src = self._store[k]
            for o in olist:
                self.bytes_pulled += int(src.nbytes)
                o._data = src._data
        _profiler.account("kvstore.bytes_pulled", self.bytes_pulled - b0)
        if t0 is not None:
            _profiler.record_op(
                "kvstore.pull", (_time.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": self.bytes_pulled - b0,
                      "type": self._kind})
        return out

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (ref: kvstore.py pushpull,
        src/kvstore/kvstore_dist.h:209 PushPullImpl)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)
        return out

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (ref: kvstore.py row_sparse_pull,
        src/kvstore/kvstore_dist.h:522 EncodeRowSparseKey). Dense storage
        with row gather on TPU."""
        assert out is not None and row_ids is not None
        t0 = _time.perf_counter() if _profiler._LIVE else None
        b0 = self.bytes_pulled
        keys, outs = _ctype_key_value(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, olist, rids in zip(keys, outs, row_ids if isinstance(
                row_ids, list) else [row_ids] * len(keys)):
            src = self._store[k]
            rows = src.take(rids, axis=0)
            # wire cost = requested rows + their ids, NOT the vocab
            self.bytes_pulled += (int(rows.nbytes) + int(rids.nbytes)) \
                * len(olist)
            for o in olist:
                from .ndarray.sparse import RowSparseNDArray, row_sparse_array
                if isinstance(o, RowSparseNDArray):
                    new = row_sparse_array((rows, rids), shape=src.shape)
                    o._indices = new._indices
                    o._values = new._values
                    o._data = new._data
                else:
                    o._data = src._data
        _profiler.account("kvstore.bytes_pulled", self.bytes_pulled - b0)
        if t0 is not None:
            _profiler.record_op(
                "kvstore.row_sparse_pull",
                (_time.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"keys": len(keys), "bytes": self.bytes_pulled - b0,
                      "type": self._kind})
        return out

    def broadcast(self, key, value, out=None, priority=0):
        """init + pull in one call (ref: kvstore.py broadcast)."""
        self.init(key, value)
        if out is not None:
            self.pull(key, out=out, priority=priority)
        return out

    # -- optimizer (server-side updates) ----------------------------------
    def set_optimizer(self, optimizer):
        """Install the optimizer; mirrors pickling the optimizer to the
        server process (ref: python/mxnet/kvstore.py set_optimizer,
        kvstore_server.py _controller)."""
        # round-trip through pickle exactly like the reference sends it
        self._optimizer = pickle.loads(pickle.dumps(optimizer))
        self._updater = opt.get_updater(self._optimizer)

    def set_updater(self, updater):
        """ref: kvstore.py _set_updater."""
        self._updater = updater

    # -- gradient compression ---------------------------------------------
    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression
        (ref: src/kvstore/gradient_compression.h:38). On TPU this applies to
        DCN (cross-slice) paths; in-process it records the config and the
        parallel backend consumes it."""
        ctype = compression_params.get("type", "2bit")
        if ctype not in ("none", "2bit"):
            raise ValueError("Unsupported compression type %r" % ctype)
        self._compression_params = dict(compression_params)
        self._compression_params.setdefault("threshold", 0.5)
        # small tensors (biases, norms) train badly when crushed to
        # {0, +-thr}; gate like the reference gates big-array handling
        self._compression_params.setdefault(
            "size_lower_bound",
            int(_getenv("MXNET_KVSTORE_SIZE_LOWER_BOUND", 4096)))
        self._compression_residuals = {}

    def _compression_active(self, merged):
        return (self._compression_params is not None
                and self._compression_params.get("type") != "none"
                and merged.size >=
                self._compression_params["size_lower_bound"])

    def _compress_reduce(self, key, merged):
        """2-bit quantize with per-key error-feedback residual; in dist
        modes the int32 words (16x smaller than fp32) are what crosses the
        wire — each worker's words are allgathered, dequantized and summed,
        exactly the server-side decompress-and-accumulate of the reference
        (ref: kvstore_dist.h compressed push path, gradient_compression.cu;
        kernels in pallas_kernels/compression.py)."""
        import jax.numpy as jnp
        from .pallas_kernels import quantize_2bit, dequantize_2bit
        thr = self._compression_params["threshold"]
        flat = merged._data.reshape(-1)
        n = flat.shape[0]
        res = self._compression_residuals.get(key)
        if res is None or res.shape != flat.shape:
            res = jnp.zeros_like(flat)
        words, new_res = quantize_2bit(flat, res, thr)
        self._compression_residuals[key] = new_res
        if self._kind.startswith("dist") and self.num_workers > 1:
            import numpy as _np
            from jax.experimental import multihost_utils
            all_words = multihost_utils.process_allgather(
                _np.asarray(words))                    # (nworker, nwords)
            deq = sum(dequantize_2bit(jnp.asarray(all_words[r]), n, thr)
                      for r in range(all_words.shape[0]))
        else:
            deq = dequantize_2bit(words, n, thr)
        return NDArray(deq.reshape(merged.shape).astype(merged._data.dtype))

    # -- optimizer-state checkpointing ------------------------------------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "updater is not initialized"
        from .base import atomic_write
        with atomic_write(fname) as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "updater is not initialized"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- distributed control ----------------------------------------------
    def _sync_reduce(self, merged):
        """Cross-process allreduce for dist modes; identity in-process."""
        if self._kind.startswith("dist") and self.num_workers > 1:
            import jax.numpy as jnp
            from .parallel import host_allreduce
            out = host_allreduce(merged)
            if not isinstance(out, NDArray):  # allgather lands on host
                out = NDArray(jnp.asarray(out))
            return out
        return merged

    def _barrier(self):
        """ref: ps::Postoffice::Barrier (src/kvstore/kvstore_dist.h:106)."""
        if self._kind.startswith("dist") and self.num_workers > 1:
            from .parallel import host_barrier
            host_barrier()

    def set_barrier_before_exit(self, barrier_before_exit):
        """ref: include/mxnet/kvstore.h:334."""
        self._barrier_before_exit = barrier_before_exit

    def send_command_to_servers(self, head, body):
        """ref: kvstore.py _send_command_to_servers — no separate server
        processes on TPU; profiler commands apply locally."""
        if head == 0 and body.startswith("set_optimizer"):
            pass

    def __del__(self):
        pass


_STR_KEY_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic memo of the str->int key mapping; values are deterministic per key)


def _str_key_int(k):
    """Stable int index for string keys (the reference hashes string keys to
    server ints via EncodeDefaultKey, src/kvstore/kvstore_dist.h:263)."""
    if k not in _STR_KEY_CACHE:
        _STR_KEY_CACHE[k] = len(_STR_KEY_CACHE)
    return _STR_KEY_CACHE[k]


def create(name="local"):
    """Factory (ref: python/mxnet/kvstore.py:716, src/kvstore/kvstore.cc:40).

    Supported: local, device, nccl (alias of device on TPU), tpu,
    dist_sync, dist_device_sync, dist_async (host-driven async
    parameter server with immediate-apply staleness semantics —
    kvstore_async.py)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    kind = name.lower()
    valid = ("local", "device", "nccl", "tpu", "dist_sync",
             "dist_device_sync", "dist_async", "dist")
    if kind not in valid:
        raise ValueError("Unknown KVStore type %r (supported: %s)"
                         % (name, ", ".join(valid)))
    if kind == "dist_async":
        # host-driven async parameter server (SURVEY §5: async has no ICI
        # analog, so it runs over a TCP transport with a server thread in
        # rank 0 applying each push immediately — the reference's
        # kvstore_dist_server.h:358 async ApplyUpdates semantics)
        from .kvstore_async import AsyncKVStore
        return AsyncKVStore()
    if kind.startswith("dist") and _getenv("MXTPU_COORDINATOR"):
        # join the job the launcher (tools/launch.py) wired via env — the
        # analog of ps-lite reading DMLC_* at KVStore::Create time
        # (ref: src/kvstore/kvstore_dist.h:50). jax.distributed must run
        # before the XLA backends initialize, so gate on the runtime's own
        # state rather than process_count() (which would initialize them).
        from jax._src import distributed as _jdist
        already = getattr(getattr(_jdist, "global_state", None),
                          "client", None) is not None
        if not already:
            from .parallel import initialize_distributed
            try:
                initialize_distributed()
            except Exception as e:  # late init, malformed env, ...
                warnings.warn(
                    "could not auto-join the distributed job (%s); call "
                    "mxnet_tpu.parallel.initialize_distributed() before "
                    "any JAX computation" % e)
    return KVStore(kind)
