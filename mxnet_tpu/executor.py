"""Executor: a bound symbolic graph compiled to ONE XLA computation.

TPU-native redesign of the reference's GraphExecutor
(ref: src/executor/graph_executor.cc:388 Init, :78 Forward, :91 Backward;
python Executor wrapper python/mxnet/executor.py). The reference binds a
graph by planning memory, attaching per-op engine closures and interpreting
the topo order through the threaded engine (RunOps graph_executor.cc:1384).
Here the whole graph is traced once into a jitted function — forward and
forward+backward each become a single fused XLA program, which is the
design seam SURVEY.md §3.3 identifies ("one CachedOp == one XLA
computation"). Memory planning (MXPlanMemory), in-place detection and op
bulking all fall out of XLA's buffer assignment and fusion instead of
hand-written passes.

grad_req semantics ('write'/'add'/'null') follow the reference
(ref: include/mxnet/op_attr_types.h OpReqType, python/mxnet/executor.py).
Aux states (BatchNorm moving stats) are updated on forward(is_train=True)
like the reference's stateful BatchNorm (ref: src/operator/nn/batch_norm-inl.h).
"""
from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .context import current_context
from .ndarray import NDArray
from .ops import registry as _registry

__all__ = ["Executor"]

from .symbol.control_flow import CONTROL_FLOW_OPS as _CONTROL_FLOW_OPS

_SIG_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic memo of deterministic signature parses; a racing duplicate insert is identical)


def _fn_params(opdef):
    sp = _SIG_CACHE.get(opdef.name)
    if sp is None:
        sig = inspect.signature(opdef.fn)
        names = set(sig.parameters)
        has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD
                         for p in sig.parameters.values())
        sp = (names, has_var_kw)
        _SIG_CACHE[opdef.name] = sp
    return sp


def _tuplify(v):
    if isinstance(v, list):
        return tuple(_tuplify(x) for x in v)
    return v


class _GraphProgram:
    """Evaluates a Symbol graph on jax values (the trace body).

    `placement` — a ({ctx_group_name: jax.Device}, default_device) pair —
    turns on group2ctx model parallelism (ref: ctx_map in
    src/executor/graph_executor.cc:388): every node's inputs are
    device_put onto its group's device, so the op executes there and
    cross-group edges become explicit transfers (the reference inserts
    the same copies via src/operator/cross_device_copy.cc). Placement
    implies eager per-node execution — per-node device pinning cannot
    live inside one fused XLA program; the real TP/PP story is
    mxnet_tpu/parallel (docs/MIGRATION.md).
    """

    def __init__(self, symbol, placement=None):
        self.symbol = symbol
        self.nodes = symbol._topo()
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.heads = list(symbol._outputs)
        self.placement = placement

    def _device_of(self, node):
        devmap, default = self.placement
        return devmap.get(node.attrs.get("ctx_group"), default)

    def run(self, values, is_train, key):
        """values: {var_name: jax array}. Returns (outputs, aux_updates)."""
        vals = {}
        aux_updates = {}
        for idx, node in enumerate(self.nodes):
            if node.is_variable():
                if node.name not in values:
                    raise MXNetError("unbound variable %r" % node.name)
                val = values[node.name]
                if self.placement:
                    val = jax.device_put(val, self._device_of(node))
                vals[(id(node), 0)] = val
                continue
            if node.op in _CONTROL_FLOW_OPS:
                from .symbol.control_flow import lower as _cf_lower
                ins = [vals[(id(src), oi)] for src, oi in node.inputs]
                if self.placement:
                    dev = self._device_of(node)
                    ins = [jax.device_put(v, dev) for v in ins]
                outs, cf_aux = _cf_lower(node, ins, is_train,
                                         jax.random.fold_in(key, idx))
                for i, o in enumerate(outs):
                    vals[(id(node), i)] = o
                # subgraph BatchNorm moving-stat writes: cut variables keep
                # their outer names, so these merge like direct aux writes
                for name, val in cf_aux.items():
                    if name in values:
                        aux_updates[name] = val
                continue
            opdef = _registry.get_op(node.op)
            pnames, has_var_kw = _fn_params(opdef)
            attrs = {}
            for k, v in node.attrs.items():
                if k.startswith("__"):
                    continue
                if has_var_kw or k in pnames:
                    attrs[k] = _tuplify(v)
            if "key" in pnames:
                attrs.setdefault("key", jax.random.fold_in(key, idx))
            if "_training" in pnames:
                attrs["_training"] = is_train
            ins = [vals[(id(src), oi)] for src, oi in node.inputs]
            if self.placement:
                # computation follows data: moving the inputs IS the
                # cross-device copy; ops whose inputs are already local
                # get a no-op
                dev = self._device_of(node)
                ins = [jax.device_put(v, dev) for v in ins]
            input_names = node.attrs.get("__input_names__")
            if input_names:
                kw = dict(zip(input_names, ins))
                kw.update(attrs)
                out = opdef.fn(**kw)
            else:
                out = opdef.fn(*ins, **attrs)
            raw = list(out) if isinstance(out, (tuple, list)) else [out]
            for i, o in enumerate(raw):
                vals[(id(node), i)] = o
            if node.op in ("BatchNorm", "batch_norm") and is_train \
                    and not node.attrs.get("use_global_stats", False) \
                    and input_names:
                momentum = float(node.attrs.get("momentum", 0.9))
                name_of = dict(zip(input_names,
                                   [src.name for src, _ in node.inputs]))
                batch_mean, batch_var = raw[1], raw[2]
                for pname, newv in (("moving_mean", batch_mean),
                                    ("moving_var", batch_var)):
                    vname = name_of.get(pname)
                    if vname is not None and vname in values:
                        aux_updates[vname] = (momentum * values[vname]
                                              + (1.0 - momentum) * newv)
        outs = [vals[(id(node), oi)] for node, oi in self.heads]
        return outs, aux_updates


class _LazyOutputs:
    """Sequence view returned by ``forward(is_train=True)``: reading it
    materializes the deferred forward via ``Executor.outputs``."""

    def __init__(self, exe):
        self._exe = exe

    def __getitem__(self, i):
        return self._exe.outputs[i]

    def __len__(self):
        return len(self._exe.outputs)

    def __iter__(self):
        return iter(self._exe.outputs)

    def __repr__(self):
        return repr(self._exe.outputs)


class Executor:
    """Bound graph with allocated arguments/gradients/aux states."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self._group2ctx = dict(group2ctx) if group2ctx else None
        placement = None
        if self._group2ctx:
            placement = ({g: c.jax_device()
                          for g, c in self._group2ctx.items()},
                         self._ctx.jax_device())
        self._prog = _GraphProgram(symbol, placement=placement)
        arg_names = self._prog.arg_names
        aux_names = self._prog.aux_names

        self.arg_dict = self._normalize(args, arg_names, "args")
        self.aux_dict = self._normalize(aux_states, aux_names, "aux_states",
                                        allow_none=True)
        self.grad_dict = self._normalize(args_grad, arg_names, "args_grad",
                                         allow_none=True, partial_ok=True)
        self._grad_req = self._normalize_req(grad_req, arg_names)
        # grads are only computed for float args with a buffer and req!=null
        self._grad_names = [n for n in arg_names
                            if self._grad_req.get(n, "null") != "null"
                            and n in self.grad_dict
                            and _np.issubdtype(self.arg_dict[n].dtype,
                                               _np.inexact)]
        self._outputs_cache = []
        self._pending = None
        self._monitor = None
        self._seed = 0

        if placement is None:
            # mxlint: disable=MX005 (per-Executor jit over a FIXED bound graph and arg shapes: one key family per bind, released with the executor)
            self._fwd = jax.jit(self._raw_forward, static_argnums=(0,))
            # mxlint: disable=MX005 (same per-Executor single-key contract as _fwd above)
            self._fwd_bwd = jax.jit(self._raw_forward_backward)
        else:
            # group2ctx pins individual nodes to devices — incompatible
            # with one fused XLA program, so the graph interpreter runs
            # eagerly with computation-follows-data placement (see
            # _GraphProgram docstring)
            self._fwd = self._raw_forward
            self._fwd_bwd = self._raw_forward_backward

    # -- binding helpers ----------------------------------------------------
    @staticmethod
    def _normalize(vals, names, what, allow_none=False, partial_ok=False):
        if vals is None:
            if allow_none:
                return {}
            raise MXNetError("%s must be provided to bind" % what)
        if isinstance(vals, dict):
            out = {}
            for k, v in vals.items():
                if k not in names:
                    continue
                out[k] = v if isinstance(v, NDArray) else NDArray(
                    jnp.asarray(v))
            missing = [n for n in names if n not in out]
            if missing and not (allow_none or partial_ok):
                raise MXNetError("missing %s for %s" % (what, missing))
            return out
        vals = list(vals)
        if len(vals) != len(names) and not partial_ok:
            raise MXNetError("%s length %d != expected %d"
                             % (what, len(vals), len(names)))
        out = {}
        for n, v in zip(names, vals):
            if v is None:
                continue
            out[n] = v if isinstance(v, NDArray) else NDArray(jnp.asarray(v))
        return out

    @staticmethod
    def _normalize_req(grad_req, arg_names):
        if isinstance(grad_req, str):
            return {n: grad_req for n in arg_names}
        if isinstance(grad_req, (list, tuple)):
            return dict(zip(arg_names, grad_req))
        return dict(grad_req)

    @classmethod
    def simple_bind(cls, symbol, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, **kwargs):
        """Allocate all arguments/grads/aux from inferred shapes
        (ref: graph_executor.cc:780 SimpleBind)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        args = {}
        for n, s in zip(arg_names, arg_shapes):
            if s is None:
                raise MXNetError("cannot infer shape of argument %r" % n)
            dt = type_dict.get(n, _np.float32)
            args[n] = NDArray(jnp.zeros(s, dt))
        aux = {n: NDArray(jnp.zeros(s, type_dict.get(n, _np.float32)))
               for n, s in zip(aux_names, aux_shapes) if s is not None}
        req = cls._normalize_req(grad_req, arg_names)
        grads = {n: NDArray(jnp.zeros_like(args[n]._data))
                 for n in arg_names
                 if req.get(n, "null") != "null"
                 and _np.issubdtype(args[n].dtype, _np.inexact)}
        return cls(symbol, ctx, args=args, args_grad=grads, grad_req=req,
                   aux_states=aux, group2ctx=group2ctx)

    # -- compiled bodies ----------------------------------------------------
    def _values(self, arg_vals, aux_vals):
        values = dict(zip(self._prog.arg_names, arg_vals))
        values.update(zip(self._prog.aux_names, aux_vals))
        return values

    def _raw_forward(self, is_train, key, arg_vals, aux_vals):
        outs, aux_up = self._prog.run(self._values(arg_vals, aux_vals),
                                      is_train, key)
        aux_out = tuple(aux_up.get(n, v) for n, v in
                        zip(self._prog.aux_names, aux_vals))
        return tuple(outs), aux_out

    def _raw_forward_backward(self, key, arg_vals, aux_vals, out_grads):
        """out_grads=None means head gradients of ones (built inside the
        traced program so no separate forward is needed to learn shapes)."""
        grad_names = self._grad_names
        fixed = {n: v for n, v in self._values(arg_vals, aux_vals).items()
                 if n not in grad_names}
        base_vals = dict(zip(self._prog.arg_names, arg_vals))

        def f(gvals):
            values = dict(fixed)
            values.update(gvals)
            outs, aux_up = self._prog.run(values, True, key)
            aux_out = tuple(aux_up.get(n, v) for n, v in
                            zip(self._prog.aux_names, aux_vals))
            return tuple(outs), aux_out

        gvals = {n: base_vals[n] for n in grad_names}
        (outs, aux_out), vjp = jax.vjp(f, gvals)
        zero_aux = tuple(jnp.zeros_like(a) for a in aux_out)
        cot = (tuple(jnp.ones_like(o) for o in outs)
               if out_grads is None else tuple(out_grads))
        (grads,) = vjp((cot, zero_aux))
        return outs, aux_out, grads

    # -- public API ---------------------------------------------------------
    def _next_key(self):
        self._seed += 1
        return jax.random.PRNGKey(self._seed)

    def _arg_vals(self):
        return tuple(self.arg_dict[n]._data for n in self._prog.arg_names)

    def _aux_vals(self):
        return tuple(self.aux_dict[n]._data for n in self._prog.aux_names)

    @property
    def outputs(self):
        """Materializes a deferred training forward on first access (same
        PRNG key that backward() will reuse, so numerics agree)."""
        if self._outputs_cache is None:
            key, arg_vals, aux_vals = self._pending
            outs, aux_out = self._fwd(True, key, arg_vals, aux_vals)
            for n, v in zip(self._prog.aux_names, aux_out):
                self.aux_dict[n]._data = v
            self._outputs_cache = [NDArray(o) for o in outs]
        return self._outputs_cache

    @outputs.setter
    def outputs(self, value):
        self._outputs_cache = value

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown argument %r" % k)
            data = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            self.arg_dict[k]._data = data.astype(self.arg_dict[k]._data.dtype)
        key = self._next_key()
        if is_train:
            # Deferred: backward() runs forward+backward fused as ONE XLA
            # program with this same key (one graph execution per step, and
            # dropout masks in the observed outputs match the gradients).
            # Outputs materialize lazily if read before backward.
            self._pending = (key, self._arg_vals(), self._aux_vals())
            self._outputs_cache = None
            if self._monitor is not None:
                for name, arr in zip(self._symbol.list_outputs(),
                                     self.outputs):
                    self._monitor(name, arr)
            return _LazyOutputs(self)
        outs, aux_out = self._fwd(False, key,
                                  self._arg_vals(), self._aux_vals())
        self._pending = None
        self.outputs = [NDArray(o) for o in outs]
        if self._monitor is not None:
            for name, arr in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor(name, arr)
        return self.outputs

    def backward(self, out_grads=None):
        """Requires a prior forward(is_train=True); runs forward+backward as
        one fused XLA program with the forward's PRNG key (rematerialisation
        is cheaper than keeping the interpreter-style per-op buffers of the
        reference)."""
        if self._pending is not None:
            key, arg_vals, aux_vals = self._pending
            self._pending = None
        else:
            key = self._next_key()
            arg_vals, aux_vals = self._arg_vals(), self._aux_vals()
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            out_grads = tuple(
                g._data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads)
        outs, aux_out, grads = self._fwd_bwd(key, arg_vals, aux_vals,
                                             out_grads)
        for n, v in zip(self._prog.aux_names, aux_out):
            self.aux_dict[n]._data = v
        self.outputs = [NDArray(o) for o in outs]
        for n in self._grad_names:
            g = grads[n]
            req = self._grad_req.get(n, "write")
            buf = self.grad_dict[n]
            if req == "add":
                buf._data = buf._data + g.astype(buf._data.dtype)
            else:
                buf._data = g.astype(buf._data.dtype)

    # convenience views matching the reference Executor
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._prog.arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._prog.arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._prog.aux_names]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = jnp.asarray(
                    v.asnumpy() if isinstance(v, NDArray) else v,
                    self.arg_dict[k]._data.dtype)
            elif not allow_extra_params:
                raise MXNetError("unknown arg param %r" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._data = jnp.asarray(
                    v.asnumpy() if isinstance(v, NDArray) else v,
                    self.aux_dict[k]._data.dtype)
            elif not allow_extra_params:
                raise MXNetError("unknown aux param %r" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes, sharing parameter values
        (ref: executor.py Executor.reshape)."""
        new_shapes = {}
        for n in self._prog.arg_names:
            if n in kwargs:
                new_shapes[n] = kwargs[n]
            else:
                new_shapes[n] = self.arg_dict[n].shape
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**new_shapes)
        args = {}
        for n, s in zip(self._prog.arg_names, arg_shapes):
            old = self.arg_dict[n]
            if tuple(old.shape) == tuple(s):
                args[n] = old
            else:
                args[n] = NDArray(jnp.zeros(s, old.dtype))
        aux = {}
        for n, s in zip(self._prog.aux_names, aux_shapes):
            old = self.aux_dict[n]
            aux[n] = old if tuple(old.shape) == tuple(s) else NDArray(
                jnp.zeros(s, old.dtype))
        grads = {n: NDArray(jnp.zeros_like(args[n]._data))
                 for n in self.grad_dict}
        return Executor(self._symbol, self._ctx, args=args, args_grad=grads,
                        grad_req=self._grad_req, aux_states=aux,
                        group2ctx=self._group2ctx)

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor = callback

    def debug_str(self):
        return self._symbol.debug_str()
