"""Base types, dtype tables and small shared helpers.

TPU-native re-design of the reference's base layer (ref: include/mxnet/base.h,
python/mxnet/base.py). There is no ctypes FFI here: the "C ABI" choke point of
the reference is replaced by the JAX/XLA runtime; this module only holds shared
plumbing (dtype canonicalisation, registries, errors).
"""
from __future__ import annotations

import contextlib
import os as _os

import numpy as _np

__all__ = [
    "MXNetError", "string_types", "numeric_types",
    "canonical_dtype", "DTYPE_NAMES", "atomic_write",
    "getenv", "getenv_dynamic",
]


def getenv(name, default=None):
    """THE env-read choke point for the framework tree.

    Semantics are exactly ``os.environ.get(name, default)`` — this
    exists so the env-var surface is statically analyzable: mxlint
    MX015 checks that every ``getenv`` call passes a literal name that
    is documented in docs/ENV_VARS.md, and MX014 checks that names read
    on traced paths are registered as compile-signature tokens
    (``ndarray/register.register_signature_token``). Direct
    ``os.environ`` reads anywhere else under ``mxnet_tpu/`` are MX015
    findings.

    Call sites that compute the variable name (the kvstore per-server
    port family) must use :func:`getenv_dynamic` and name the
    documented family instead."""
    return _os.environ.get(name, default)


def getenv_dynamic(name, default=None, family=None):
    """Env read with a COMPUTED name (``family`` is the documented base
    name). The only sanctioned form for derived variables like
    ``MXTPU_ASYNC_PS_PORT_<s>``: mxlint MX015 cannot resolve a computed
    name, so the call site declares the ENV_VARS.md row it derives from
    and the checker validates the family literal instead."""
    del family  # documentation-only: consumed by mxlint, not at runtime
    return _os.environ.get(name, default)


class MXNetError(RuntimeError):
    """Framework-level error (name kept for API parity with the reference,
    ref: python/mxnet/base.py:75)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)

# Canonical dtype table. bfloat16 is first-class on TPU (the reference's fp16
# AMP path maps to bf16 here). ref: python/mxnet/base.py dtype handling.
import jax.numpy as _jnp

DTYPE_NAMES = {
    "float32": _jnp.float32,
    "float64": _jnp.float64,
    "float16": _jnp.float16,
    "bfloat16": _jnp.bfloat16,
    "uint8": _jnp.uint8,
    "int8": _jnp.int8,
    "int32": _jnp.int32,
    "int64": _jnp.int64,
    "bool": _jnp.bool_,
}


@contextlib.contextmanager
def atomic_write(fname, mode="wb"):
    """Crash-consistent file publication: yields an open file over a
    sibling temp path, and ``os.replace``-renames it onto ``fname`` only
    after the body completed. The profiler's continuous-dump idiom
    (profiler._atomic_json_write) generalized for every checkpoint
    writer (nd.save, symbol.save, Trainer.save_states,
    parallel.CheckpointManager): a crash — or an injected
    ``checkpoint.save`` fault — mid-save can never leave a corrupt or
    half-written file at the published path; the previous checkpoint
    stays intact and the temp file is removed.

    The ``checkpoint.save`` fault point fires BETWEEN the temp write and
    the rename — the worst possible crash instant, which is exactly what
    the atomicity contract must survive (tests/test_faultpoints.py)."""
    from ._debug import faultpoint as _faultpoint
    tmp = "%s.tmp.%d" % (fname, _os.getpid())
    try:
        with open(tmp, mode) as f:
            yield f
        if _faultpoint.ACTIVE:
            _faultpoint.check("checkpoint.save")
        _os.replace(tmp, fname)
    except BaseException:
        try:
            _os.remove(tmp)
        except OSError:
            pass
        raise


def is_inexact_dtype(dt):
    """True for float dtypes INCLUDING ml_dtypes extensions (bfloat16,
    fp8...) that numpy's issubdtype does not place under np.inexact.
    Single source of truth for 'is this differentiable?' checks."""
    try:
        return _jnp.issubdtype(dt, _jnp.inexact)
    except TypeError:
        return False


def canonical_dtype(dtype):
    """Map a user dtype spec (str | numpy dtype | jnp dtype | None) to a numpy
    dtype object usable by jax."""
    if dtype is None:
        return _np.dtype("float32")
    if isinstance(dtype, str):
        if dtype not in DTYPE_NAMES:
            raise TypeError("unknown dtype %r" % (dtype,))
        return _np.dtype(DTYPE_NAMES[dtype])
    return _np.dtype(dtype)


class _Registry:
    """Minimal named registry (replaces dmlc::Registry,
    ref: 3rdparty/dmlc-core dmlc/registry.h usage across src/)."""

    def __init__(self, kind):
        self.kind = kind
        self._entries = {}

    def register(self, name, obj=None):
        if obj is None:  # decorator form
            def _reg(o):
                self._entries[name.lower()] = o
                return o
            return _reg
        self._entries[name.lower()] = obj
        return obj

    def get(self, name):
        try:
            return self._entries[name.lower()]
        except KeyError:
            raise KeyError("%s %r not registered. Known: %s"
                           % (self.kind, name, sorted(self._entries)))

    def __contains__(self, name):
        return name.lower() in self._entries

    def entries(self):
        return dict(self._entries)
