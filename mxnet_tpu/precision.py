"""Matmul/conv precision policy (VERDICT r4 item 3).

The reference's fp32 dot/conv is true fp32 because it dispatches to BLAS
(ref: 3rdparty/mshadow/mshadow/dot_engine-inl.h Strassen/gemm dispatch);
on TPU the MXU multiplies in bfloat16 by default, so fp32 users silently
get bf16-pass accuracy (measured: `dot` 21,001 ULP vs CPU at default,
3 ULP at highest — BENCH_r04.json `matmul_family_ulp`). This module gives
the reference's implicit guarantee an explicit, controllable surface.

Three layers, most-specific wins:

  1. per-call ``precision=`` on the matmul family (`dot`, `batch_dot`,
     `linalg_gemm`/`gemm2`/`trmm`/`syrk`, `FullyConnected`,
     `Convolution`, `Deconvolution`)
  2. process-global `set_matmul_precision()` / scoped
     `matmul_precision()` context manager
  3. the `MXTPU_MATMUL_PRECISION` env var, read once at package import
     (docs/ENV_VARS.md)

All three resolve to XLA's dot/conv `precision_config`, so one policy
governs every frontend (nd/sym/gluon/np) and every compiled graph —
there is no per-kernel dispatch table to keep in sync.

Values:
  - ``default``: fastest MXU path (one bf16 pass per operand). The
    TPU-native default, ~matches fp16/TF32 tensor-core training regimes.
  - ``float32``: 3-pass bf16x3 emulation of fp32 multiplies — the knob
    for reference-parity fp32 accuracy at ~1/3 MXU throughput.
  - ``highest``: strictest the backend offers (6-pass on current TPUs;
    equal to float32 on many generations, never weaker).
JAX's extra names (``high``, ``bfloat16``, ``tensorfloat32``, ...) pass
through unvalidated for forward compat.
"""
from __future__ import annotations

import contextlib
import os

import jax
from .base import getenv as _getenv

__all__ = ["set_matmul_precision", "get_matmul_precision",
           "matmul_precision"]

ENV_VAR = "MXTPU_MATMUL_PRECISION"
_NAMES = ("default", "float32", "highest")


def set_matmul_precision(precision):
    """Set the process-global matmul/conv precision; returns the previous
    value. ``None`` and ``"default"`` both restore the backend default."""
    prev = get_matmul_precision()
    if precision is None:
        precision = "default"
    jax.config.update("jax_default_matmul_precision", precision)
    return prev


def get_matmul_precision():
    """Current global policy name ('default' when unset)."""
    val = jax.config.jax_default_matmul_precision
    return "default" if val is None else str(val)


@contextlib.contextmanager
def matmul_precision(precision):
    """Scoped precision override::

        with mx.matmul_precision("float32"):
            y = mx.nd.dot(a, b)          # true-fp32 accumulation

    Composes with jit: entering the context changes the trace, so cached
    executables keyed on the old policy are not reused.
    """
    with jax.default_matmul_precision(
            "default" if precision is None else precision):
        yield


def _apply_env():
    """Honor MXTPU_MATMUL_PRECISION at import (package __init__)."""
    val = _getenv(ENV_VAR)
    if val:
        set_matmul_precision(val)


_apply_env()
