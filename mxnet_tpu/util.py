"""Utility functions: NumPy-semantics scopes, decorators, misc helpers.

ref: python/mxnet/util.py. The reference gates zero-dim/zero-size shape
support (``set_np_shape``, util.py:53) and the NumPy array namespace
(``set_np``/``np_array``, util.py:584,364) behind thread-local scopes because
its legacy C++ shape encoding reserved 0-dim as "unknown". jnp is natively
NumPy-shaped, so here the scopes only steer *frontend* behavior: which array
type ops return (classic NDArray vs mx.np ndarray) and shape legality checks
in the legacy API.
"""
from __future__ import annotations

import functools
import os
import threading

__all__ = [
    "makedirs", "get_gpu_count", "get_gpu_memory",
    "set_np_shape", "is_np_shape", "np_shape", "use_np_shape",
    "set_np", "reset_np", "np_array", "is_np_array", "use_np_array",
    "use_np", "set_module", "wraps_safely",
]

_scope = threading.local()


def _get(name, default=False):
    return getattr(_scope, name, default)


def makedirs(d):
    """ref: util.py:30."""
    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    """ref: util.py:40. Counts accelerator devices (TPU chips here)."""
    import jax
    return len([d for d in jax.devices() if d.platform != "cpu"])


def get_gpu_memory(gpu_dev_id):
    """ref: util.py:46. (free, total) bytes for one accelerator device."""
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    d = devs[gpu_dev_id]
    st = d.memory_stats() or {}
    total = st.get("bytes_limit", 0)
    return total - st.get("bytes_in_use", 0), total


# -- np_shape scope (ref: util.py:53-227) ------------------------------------

def set_np_shape(active):
    """Turn on/off zero-dim & zero-size shape semantics in the classic API
    (ref: util.py:53). Returns the previous state."""
    prev = _get("np_shape")
    _scope.np_shape = bool(active)
    return prev


def is_np_shape():
    """ref: util.py:98."""
    return _get("np_shape")


class _Scope:
    def __init__(self, name, active):
        self._name = name
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = _get(self._name)
        setattr(_scope, self._name, self._active)
        return self

    def __exit__(self, *a):
        setattr(_scope, self._name, self._prev)


def np_shape(active=True):
    """``with mx.util.np_shape():`` scope (ref: util.py:160)."""
    return _Scope("np_shape", active)


def wraps_safely(wrapped, assigned=functools.WRAPPER_ASSIGNMENTS):
    """functools.wraps tolerant of missing attrs (ref: util.py:229)."""
    return functools.wraps(wrapped,
                           [a for a in assigned if hasattr(wrapped, a)])


def use_np_shape(func):
    """Decorator running ``func`` under np_shape scope (ref: util.py:240).
    Works on functions and classes."""
    if isinstance(func, type):
        for name, m in vars(func).items():
            if callable(m):
                setattr(func, name, use_np_shape(m))
        return func

    @wraps_safely(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)
    return wrapper


# -- np_array scope (ref: util.py:339-560) -----------------------------------

def np_array(active=True):
    """Scope: ops create mx.np ndarrays instead of classic NDArrays
    (ref: util.py:364)."""
    return _Scope("np_array", active)


def is_np_array():
    """ref: util.py:393."""
    return _get("np_array")


def use_np_array(func):
    """ref: util.py:416."""
    if isinstance(func, type):
        for name, m in vars(func).items():
            if callable(m):
                setattr(func, name, use_np_array(m))
        return func

    @wraps_safely(func)
    def wrapper(*args, **kwargs):
        with np_array(True):
            return func(*args, **kwargs)
    return wrapper


def use_np(func):
    """np_shape + np_array combined decorator (ref: util.py:498)."""
    return use_np_shape(use_np_array(func))


def set_np(shape=True, array=True):
    """Globally activate NumPy semantics (ref: util.py:584)."""
    if array and not shape:
        raise ValueError("NumPy array semantics require NumPy shape "
                         "semantics (ref: util.py:594)")
    set_np_shape(shape)
    _scope.np_array = bool(array)


def reset_np():
    """ref: util.py:602."""
    set_np(False, False)
    _scope.np_array = False
    _scope.np_shape = False


def set_module(module):
    """Decorator overriding __module__ for docs (ref: util.py:321)."""
    def deco(obj):
        if module is not None:
            obj.__module__ = module
        return obj
    return deco
