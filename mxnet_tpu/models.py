"""Top-level model registry: alias of `gluon.model_zoo.vision`.

Convenience namespace so `mx.models.get_model('resnet50_v1')` works alongside
the reference-compatible `mx.gluon.model_zoo.vision.get_model`.
"""
from .gluon.model_zoo import vision
from .gluon.model_zoo.vision import get_model  # noqa: F401

__all__ = ["vision", "get_model"]
