"""Python-side dispatcher for the native training C ABI.

The C entry points in src/c_api_runtime.cc (MXTNDArray*,
MXTImperativeInvoke, MXTAutograd*) marshal handles and strings, then
call into this module — mirroring how the reference's src/c_api/
c_api_ndarray.cc:81 dispatches into Imperative::Invoke. Keeping the
dispatch here means the full op registry, autograd tape, and XLA
compile cache are shared with the Python frontend; the C ABI is a seam,
not a second runtime.

Every function takes/returns plain Python objects; the C side holds
NDArray references as PyObject handles.
"""
from __future__ import annotations

import ast

import numpy as np

from . import autograd
from .ndarray import NDArray
from .ndarray import register as _register

__all__ = ["create", "from_bytes", "to_bytes", "shape_of", "dtype_of",
           "invoke", "mark_variables", "record_start", "record_stop",
           "backward", "grad_of", "wait_all", "load_symbol_json"]

_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
           4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}
_DTYPE_IDS = {v: k for k, v in _DTYPES.items()}


def create(shape, dtype_id):
    import mxnet_tpu as mx
    return mx.nd.zeros(tuple(shape), dtype=_DTYPES[int(dtype_id)])


def from_bytes(shape, dtype_id, raw):
    arr = np.frombuffer(raw, _DTYPES[int(dtype_id)]).reshape(tuple(shape))
    return NDArray(np.ascontiguousarray(arr))


def to_bytes(arr):
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def shape_of(arr):
    return tuple(int(s) for s in arr.shape)


def dtype_of(arr):
    return _DTYPE_IDS.get(str(arr.dtype), 0)


def _parse(v):
    """Parse a C-string op param the way the reference's param structs do
    (dmlc::Parameter parsing): python literals, else raw string."""
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def invoke(op_name, inputs, keys, vals):
    """MXTImperativeInvoke core (ref: c_api_ndarray.cc:132
    MXImperativeInvokeEx -> Imperative::Invoke). Shares the dispatch
    choke point with the Python frontend (AMP hooks and all)."""
    kwargs = {k: _parse(v) for k, v in zip(keys, vals)}
    out = _register.invoke_by_name(op_name, *inputs, **kwargs)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def mark_variables(arrs):
    """ref: c_api.h MXAutogradMarkVariables."""
    for a in arrs:
        a.attach_grad()


_RECORD_SCOPES = []


def record_start():
    """ref: MXAutogradSetIsRecording(1) + SetIsTraining(1) — an absolute
    setter like the reference, not a nesting scope: repeated (1) calls
    are idempotent."""
    if not _RECORD_SCOPES:
        scope = autograd.record()
        scope.__enter__()
        _RECORD_SCOPES.append(scope)


def record_stop():
    while _RECORD_SCOPES:
        _RECORD_SCOPES.pop().__exit__(None, None, None)


def backward(outputs):
    """ref: MXAutogradBackwardEx (c_api.h:1222)."""
    if len(outputs) == 1:
        outputs[0].backward()
    else:
        autograd.backward(outputs)


def grad_of(arr):
    g = arr.grad
    if g is None:
        raise ValueError("array has no gradient (not marked, or no "
                         "backward has run)")
    return g


def wait_all():
    """ref: MXNDArrayWaitAll (c_api.h:528) barrier semantics."""
    import mxnet_tpu as mx
    mx.nd.waitall()


def load_symbol_json(path):
    import mxnet_tpu as mx
    return mx.sym.load(path)
