"""Python-side dispatcher for the native training C ABI.

The C entry points in src/c_api_runtime.cc (MXTNDArray*,
MXTImperativeInvoke, MXTAutograd*) marshal handles and strings, then
call into this module — mirroring how the reference's src/c_api/
c_api_ndarray.cc:81 dispatches into Imperative::Invoke. Keeping the
dispatch here means the full op registry, autograd tape, and XLA
compile cache are shared with the Python frontend; the C ABI is a seam,
not a second runtime.

Every function takes/returns plain Python objects; the C side holds
NDArray references as PyObject handles.
"""
from __future__ import annotations

import ast

import numpy as np

from . import autograd
from .ndarray import NDArray
from .ndarray import register as _register

__all__ = ["create", "from_bytes", "to_bytes", "shape_of", "dtype_of",
           "invoke", "mark_variables", "record_start", "record_stop",
           "backward", "grad_of", "wait_all", "load_symbol_json"]

_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
           4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}
_DTYPE_IDS = {v: k for k, v in _DTYPES.items()}


def create(shape, dtype_id):
    import mxnet_tpu as mx
    return mx.nd.zeros(tuple(shape), dtype=_DTYPES[int(dtype_id)])


def from_bytes(shape, dtype_id, raw):
    arr = np.frombuffer(raw, _DTYPES[int(dtype_id)]).reshape(tuple(shape))
    return NDArray(np.ascontiguousarray(arr))


def to_bytes(arr):
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def shape_of(arr):
    return tuple(int(s) for s in arr.shape)


def dtype_of(arr):
    return _DTYPE_IDS.get(str(arr.dtype), 0)


def _parse(v):
    """Parse a C-string op param the way the reference's param structs do
    (dmlc::Parameter parsing): python literals, else raw string."""
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def invoke(op_name, inputs, keys, vals):
    """MXTImperativeInvoke core (ref: c_api_ndarray.cc:132
    MXImperativeInvokeEx -> Imperative::Invoke). Shares the dispatch
    choke point with the Python frontend (AMP hooks and all)."""
    from .ops import registry as _registry
    kwargs = {k: _parse(v) for k, v in zip(keys, vals)}
    try:
        opdef = _registry.get_op(op_name)
    except KeyError:
        # the fused optimizer update ops live in the nd namespace, not
        # the registry (ndarray/optimizer_ops.py) — the reference
        # registers those as ops too, so resolve exactly that family
        # here (an allowlist: arbitrary nd attributes like save/load
        # must NOT be invocable through the C op surface)
        from .ndarray import optimizer_ops as _opt_ops
        if op_name not in _opt_ops.__all__:
            raise KeyError("no such operator: %r" % op_name)
        out = getattr(_opt_ops, op_name)(*inputs, **kwargs)
    else:
        out = _register.invoke(opdef, inputs, kwargs)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def mark_variables(arrs):
    """ref: c_api.h MXAutogradMarkVariables."""
    for a in arrs:
        a.attach_grad()


_RECORD_SCOPES = []


def record_start():
    """ref: MXAutogradSetIsRecording(1) + SetIsTraining(1) — an absolute
    setter like the reference, not a nesting scope: repeated (1) calls
    are idempotent."""
    if not _RECORD_SCOPES:
        scope = autograd.record()
        scope.__enter__()
        _RECORD_SCOPES.append(scope)


def record_stop():
    while _RECORD_SCOPES:
        _RECORD_SCOPES.pop().__exit__(None, None, None)


def backward(outputs):
    """ref: MXAutogradBackwardEx (c_api.h:1222)."""
    if len(outputs) == 1:
        outputs[0].backward()
    else:
        autograd.backward(outputs)


def grad_of(arr):
    g = arr.grad
    if g is None:
        raise ValueError("array has no gradient (not marked, or no "
                         "backward has run)")
    return g


def wait_all():
    """ref: MXNDArrayWaitAll (c_api.h:528) barrier semantics."""
    import mxnet_tpu as mx
    mx.nd.waitall()


def load_symbol_json(path):
    import mxnet_tpu as mx
    return mx.sym.load(path)


# -- Symbol family (ref: MXSymbol* section of include/mxnet/c_api.h) --------

def symbol_from_json(json_str):
    import mxnet_tpu as mx
    return mx.sym.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_save(sym, path):
    sym.save(path)


def symbol_var(name):
    import mxnet_tpu as mx
    return mx.sym.var(name)


class _AtomicOp:
    """An op-with-params awaiting composition (the two-step
    MXSymbolCreateAtomicSymbol -> MXSymbolCompose flow of the reference
    C ABI; ref: c_api_symbolic.cc)."""

    def __init__(self, op_name, attrs):
        from .ops import registry as _registry
        _registry.get_op(op_name)  # fail fast on unknown ops
        self.op_name = op_name
        self.attrs = attrs


def symbol_create_atomic(op_name, keys, vals):
    return _AtomicOp(op_name, {k: _parse(v) for k, v in zip(keys, vals)})


def symbol_compose(atomic, name, keys, args):
    """Compose an atomic op with input symbols. `keys` empty => positional
    (the reference accepts both; ref: MXSymbolCompose c_api.h)."""
    from .symbol.register import make_symbol_op_func
    from .ops import registry as _registry
    opdef = _registry.get_op(atomic.op_name)
    fn = make_symbol_op_func(opdef, atomic.op_name)
    kwargs = dict(atomic.attrs)
    if name:
        kwargs["name"] = name
    if keys:
        kwargs.update(dict(zip(keys, args)))
        return fn(**kwargs)
    return fn(*args, **kwargs)


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_name(sym):
    n = getattr(sym, "name", None)
    return n if n is not None else ""


def symbol_infer_shape(sym, names, shapes):
    """Returns (arg_shapes, out_shapes, aux_shapes) given provided input
    shapes (ref: MXSymbolInferShape)."""
    provided = {n: tuple(s) for n, s in zip(names, shapes)}
    arg, out, aux = sym.infer_shape(**provided)
    def _clean(lst):
        return [tuple(int(d) for d in s) if s is not None else () for s in lst]
    return _clean(arg), _clean(out), _clean(aux)


# -- Executor family (ref: MXExecutor* / graph_executor.cc) -----------------

def executor_simple_bind(sym, names, shapes, grad_req):
    from .executor import Executor
    provided = {n: tuple(s) for n, s in zip(names, shapes)}
    return Executor.simple_bind(sym, grad_req=grad_req, **provided)


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def executor_outputs(ex):
    return list(ex.outputs)


def executor_backward(ex, out_grads):
    ex.backward(out_grads if out_grads else None)


def executor_arg(ex, name):
    return ex.arg_dict[name]


def executor_grad(ex, name):
    g = ex.grad_dict.get(name)
    if g is None:
        raise KeyError("argument %r has no gradient buffer" % name)
    return g


def executor_aux(ex, name):
    return ex.aux_dict[name]


# -- KVStore family (ref: MXKVStore* c_api.h; src/kvstore/kvstore.cc:40) ----

def kv_create(kind):
    import mxnet_tpu as mx
    return mx.kv.create(kind)


def kv_init(kv, key, arr):
    kv.init(key, arr)


def kv_push(kv, key, arr, priority):
    kv.push(key, arr, priority=priority)


def kv_pull(kv, key, out, priority):
    kv.pull(key, out=out, priority=priority)


def kv_pushpull(kv, key, arr, out, priority):
    kv.pushpull(key, arr, out=out, priority=priority)


def kv_rank(kv):
    return int(kv.rank)


def kv_size(kv):
    return int(kv.num_workers)


def kv_type(kv):
    return str(kv.type)


def kv_barrier(kv):
    """Global barrier across workers (ref: MXKVStoreBarrier)."""
    kv._barrier()


def kv_set_optimizer(kv, name, keys, vals):
    import mxnet_tpu.optimizer as opt
    params = {k: _parse(v) for k, v in zip(keys, vals)}
    kv.set_optimizer(opt.create(name, **params))


# -- DataIter family (ref: MXDataIter* c_api.h; src/io/io.cc registry) ------

_ITER_NAMES = ("MNISTIter", "CSVIter", "LibSVMIter", "ImageRecordIter")


def list_data_iters():
    return list(_ITER_NAMES)


class _IterCursor:
    """Holds the current batch so GetData/GetLabel have stable handles
    (the reference iterator's current DataBatch)."""

    def __init__(self, it):
        self.it = it
        self.batch = None


def data_iter_create(name, keys, vals):
    import mxnet_tpu.io as io
    import mxnet_tpu.image as image
    params = {k: _parse(v) for k, v in zip(keys, vals)}
    if name == "ImageRecordIter":
        from .io.image_iter import ImageRecordIter
        return _IterCursor(ImageRecordIter(**params))
    cls = getattr(io, name, None)
    if cls is None:
        cls = getattr(image, name, None)
    if cls is None:
        raise ValueError("unknown data iterator %r (have: %s)"
                         % (name, ", ".join(_ITER_NAMES)))
    return _IterCursor(cls(**params))


def data_iter_next(cur):
    try:
        cur.batch = cur.it.next()
        return 1
    except StopIteration:
        cur.batch = None
        return 0


def data_iter_data(cur):
    if cur.batch is None:
        raise RuntimeError("no current batch (call MXTDataIterNext first)")
    return cur.batch.data[0]


def data_iter_label(cur):
    if cur.batch is None:
        raise RuntimeError("no current batch (call MXTDataIterNext first)")
    return cur.batch.label[0]


def data_iter_reset(cur):
    cur.it.reset()
    cur.batch = None


# -- NDArray save/load (ref: MXNDArraySave/Load c_api.h:638-672) ------------

def nd_save(fname, arrays, names):
    import mxnet_tpu as mx
    if names:
        mx.nd.save(fname, dict(zip(names, arrays)))
    else:
        mx.nd.save(fname, list(arrays))


def nd_load(fname):
    import mxnet_tpu as mx
    data = mx.nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return names, [data[n] for n in names]
    return [], list(data)


def set_data(dst, src):
    """Device-side value copy dst <- src, no host round trip
    (ref: MXNDArraySyncCopyFromNDArray c_api.h)."""
    import jax.numpy as jnp
    if tuple(dst.shape) != tuple(src.shape):
        raise ValueError("MXTNDArrayCopyFrom: shape mismatch (dst %s, "
                         "src %s)" % (tuple(dst.shape), tuple(src.shape)))
    dst._data = jnp.asarray(src._data, dst._data.dtype)


def copy_from_bytes(arr, raw):
    """In-place value update (ref: MXNDArraySyncCopyFromCPU c_api.h:456)."""
    import jax.numpy as jnp
    new = np.frombuffer(raw, str(arr.dtype)).reshape(arr.shape)
    arr._data = jnp.asarray(np.ascontiguousarray(new))


# -- misc (seed/op list/lib loading) ----------------------------------------
# (the version constant lives C-side in MXTGetVersion, c_api_symbol.cc)

def random_seed(seed):
    import mxnet_tpu as mx
    mx.random.seed(int(seed))


def list_all_ops():
    from .ops import registry as _registry
    return sorted(set(_registry.list_ops()))


def load_lib(path):
    from . import lib_api
    lib_api.load(path)


# -- NDArray views (ref: MXNDArrayReshape/Slice/At c_api.h) -----------------

def nd_reshape(arr, shape):
    return arr.reshape(tuple(int(d) for d in shape))


def nd_slice(arr, begin, end):
    # the slice op takes per-axis tuples (ref: slice-inl.h SliceParam)
    return arr.slice((int(begin),), (int(end),))


def nd_at(arr, idx):
    return arr[int(idx)]


# -- autograd flags (ref: MXAutogradIsRecording/IsTraining/SetIsTraining) ---

def autograd_is_recording():
    return 1 if autograd.is_recording() else 0


def autograd_is_training():
    return 1 if autograd.is_training() else 0


def autograd_set_training(flag):
    autograd.set_training(bool(flag))


# -- profiler controls (ref: MXSetProcessProfilerConfig/State, MXDumpProfile)

def profiler_set_config(keys, vals):
    from . import profiler
    kwargs = {}
    for k, v in zip(keys, vals):
        kwargs[k] = _parse(v)
    profiler.set_config(**kwargs)


def profiler_set_state(state):
    from . import profiler
    profiler.set_state("run" if int(state) else "stop")


def profiler_dump():
    from . import profiler
    profiler.dump()


# -- Symbol attributes / views (ref: MXSymbolGetAttr/SetAttr/ListAttr,
#    MXSymbolGetInternals/GetOutput c_api.h) --------------------------------

def symbol_attr(sym, key):
    v = sym.attr(key)
    # None = missing; any string (even "") = present — the C side maps
    # this onto the (out, success) pair like the reference
    return None if v is None else str(v)


def symbol_set_attr(sym, key, val):
    # store the RAW string (ref: MXSymbolSetAttr keeps values verbatim;
    # a parse/re-stringify round trip would mutate "1.50" -> "1.5")
    sym._set_attr(**{key: val})


def symbol_attr_json(sym):
    import json as _json
    return _json.dumps(sym.attr_dict)


def symbol_get_internals(sym):
    return sym.get_internals()


def symbol_get_output(sym, index):
    return sym[int(index)]


def symbol_copy(sym):
    import copy as _copy
    return _copy.deepcopy(sym)
