"""Python-side dispatcher for the native training C ABI.

The C entry points in src/c_api_runtime.cc (MXTNDArray*,
MXTImperativeInvoke, MXTAutograd*) marshal handles and strings, then
call into this module — mirroring how the reference's src/c_api/
c_api_ndarray.cc:81 dispatches into Imperative::Invoke. Keeping the
dispatch here means the full op registry, autograd tape, and XLA
compile cache are shared with the Python frontend; the C ABI is a seam,
not a second runtime.

Every function takes/returns plain Python objects; the C side holds
NDArray references as PyObject handles.
"""
from __future__ import annotations

import ast
import threading as _threading

import numpy as np

from . import autograd
from .ndarray import NDArray
from .ndarray import register as _register
from .base import getenv as _getenv

__all__ = ["create", "from_bytes", "to_bytes", "shape_of", "dtype_of",
           "invoke", "mark_variables", "record_start", "record_stop",
           "backward", "grad_of", "wait_all", "load_symbol_json"]

_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
           4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}
_DTYPE_IDS = {v: k for k, v in _DTYPES.items()}


def create(shape, dtype_id):
    import mxnet_tpu as mx
    return mx.nd.zeros(tuple(shape), dtype=_DTYPES[int(dtype_id)])


def from_bytes(shape, dtype_id, raw):
    arr = np.frombuffer(raw, _DTYPES[int(dtype_id)]).reshape(tuple(shape))
    return NDArray(np.ascontiguousarray(arr))


def to_bytes(arr):
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def shape_of(arr):
    return tuple(int(s) for s in arr.shape)


def dtype_of(arr):
    return _DTYPE_IDS.get(str(arr.dtype), 0)


def _parse(v):
    """Parse a C-string op param the way the reference's param structs do
    (dmlc::Parameter parsing): python literals, else raw string."""
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def invoke(op_name, inputs, keys, vals):
    """MXTImperativeInvoke core (ref: c_api_ndarray.cc:132
    MXImperativeInvokeEx -> Imperative::Invoke). Shares the dispatch
    choke point with the Python frontend (AMP hooks and all)."""
    from .ops import registry as _registry
    kwargs = {k: _parse(v) for k, v in zip(keys, vals)}
    # the fused optimizer update ops keep the reference's IN-PLACE
    # calling convention on this surface (state mutated, one output) —
    # the nd wrappers (ndarray/optimizer_ops.py) shadow the pure
    # registry forms here exactly as they do in the nd namespace
    if op_name in _inplace_update_ops():
        from .ndarray import optimizer_ops as _opt_ops
        out = getattr(_opt_ops, op_name)(*inputs, **kwargs)
    else:
        try:
            opdef = _registry.get_op(op_name)
        except KeyError:
            raise KeyError("no such operator: %r" % op_name)
        out = _register.invoke(opdef, inputs, kwargs)
    return list(out) if isinstance(out, (tuple, list)) else [out]


_INPLACE_UPDATE_OPS = None


def _inplace_update_ops():
    global _INPLACE_UPDATE_OPS
    if _INPLACE_UPDATE_OPS is None:
        from .ndarray import optimizer_ops as _opt_ops
        _INPLACE_UPDATE_OPS = frozenset(_opt_ops.__all__)
    return _INPLACE_UPDATE_OPS


def mark_variables(arrs):
    """ref: c_api.h MXAutogradMarkVariables."""
    for a in arrs:
        a.attach_grad()


# per-thread open record scope: autograd recording state is thread-local
# (both here and in the reference), so a second C-ABI thread toggling
# recording must not pop a scope the first thread opened
_RECORD_SCOPES = _threading.local()


def _record_stack():
    stack = getattr(_RECORD_SCOPES, "stack", None)
    if stack is None:
        stack = _RECORD_SCOPES.stack = []
    return stack


def record_start():
    """ref: MXAutogradSetIsRecording(1) + SetIsTraining(1) — an absolute
    setter like the reference, not a nesting scope: repeated (1) calls
    are idempotent."""
    stack = _record_stack()
    if not stack:
        scope = autograd.record()
        scope.__enter__()
        stack.append(scope)


def record_stop():
    stack = _record_stack()
    while stack:
        stack.pop().__exit__(None, None, None)


def backward(outputs):
    """ref: MXAutogradBackwardEx (c_api.h:1222)."""
    if len(outputs) == 1:
        outputs[0].backward()
    else:
        autograd.backward(outputs)


def grad_of(arr):
    g = arr.grad
    if g is None:
        raise ValueError("array has no gradient (not marked, or no "
                         "backward has run)")
    return g


def wait_all():
    """ref: MXNDArrayWaitAll (c_api.h:528) barrier semantics."""
    import mxnet_tpu as mx
    mx.nd.waitall()


def load_symbol_json(path):
    import mxnet_tpu as mx
    return mx.sym.load(path)


# -- Symbol family (ref: MXSymbol* section of include/mxnet/c_api.h) --------

def symbol_from_json(json_str):
    import mxnet_tpu as mx
    return mx.sym.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_save(sym, path):
    sym.save(path)


def symbol_var(name):
    import mxnet_tpu as mx
    return mx.sym.var(name)


class _AtomicOp:
    """An op-with-params awaiting composition (the two-step
    MXSymbolCreateAtomicSymbol -> MXSymbolCompose flow of the reference
    C ABI; ref: c_api_symbolic.cc)."""

    def __init__(self, op_name, attrs):
        from .ops import registry as _registry
        _registry.get_op(op_name)  # fail fast on unknown ops
        self.op_name = op_name
        self.attrs = attrs


def symbol_create_atomic(op_name, keys, vals):
    return _AtomicOp(op_name, {k: _parse(v) for k, v in zip(keys, vals)})


def symbol_compose(atomic, name, keys, args):
    """Compose an atomic op with input symbols. `keys` empty => positional
    (the reference accepts both; ref: MXSymbolCompose c_api.h)."""
    from .symbol.register import make_symbol_op_func
    from .ops import registry as _registry
    opdef = _registry.get_op(atomic.op_name)
    fn = make_symbol_op_func(opdef, atomic.op_name)
    kwargs = dict(atomic.attrs)
    if name:
        kwargs["name"] = name
    if keys:
        kwargs.update(dict(zip(keys, args)))
        return fn(**kwargs)
    return fn(*args, **kwargs)


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_name(sym):
    n = getattr(sym, "name", None)
    return n if n is not None else ""


def symbol_infer_shape(sym, names, shapes):
    """Returns (arg_shapes, out_shapes, aux_shapes) given provided input
    shapes (ref: MXSymbolInferShape)."""
    provided = {n: tuple(s) for n, s in zip(names, shapes)}
    arg, out, aux = sym.infer_shape(**provided)
    def _clean(lst):
        return [tuple(int(d) for d in s) if s is not None else () for s in lst]
    return _clean(arg), _clean(out), _clean(aux)


# -- Executor family (ref: MXExecutor* / graph_executor.cc) -----------------

def executor_simple_bind(sym, names, shapes, grad_req):
    from .executor import Executor
    provided = {n: tuple(s) for n, s in zip(names, shapes)}
    return Executor.simple_bind(sym, grad_req=grad_req, **provided)


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def executor_outputs(ex):
    return list(ex.outputs)


def executor_backward(ex, out_grads):
    ex.backward(out_grads if out_grads else None)


def executor_arg(ex, name):
    return ex.arg_dict[name]


def executor_grad(ex, name):
    g = ex.grad_dict.get(name)
    if g is None:
        raise KeyError("argument %r has no gradient buffer" % name)
    return g


def executor_aux(ex, name):
    return ex.aux_dict[name]


# -- CachedOp family (ref: MXCreateCachedOp c_api.h:1241; the jit seam) -----

def cachedop_create(sym, keys, vals):
    """MXTCachedOpCreate core: flags mirror CachedOpConfig
    (ref: cached_op.h:35 — static_alloc/static_shape/inline_limit)."""
    from .jit import CachedOp
    known = ("static_alloc", "static_shape", "inline_limit")
    kwargs = {}
    flags = []
    for k, v in zip(keys, vals):
        pv = _parse(v)
        if k in known:
            kwargs[k] = pv
        else:
            flags.append((k, pv))
    return CachedOp(sym, flags=flags, **kwargs)


def cachedop_invoke(op, inputs):
    """MXTCachedOpInvoke core: always returns a list of NDArrays."""
    out = op(*inputs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def cachedop_stats(op):
    """(total calls, traces+compiles) — the second same-signature call
    must show compiles == 1 (the cache-hit proof the C demo asserts)."""
    return int(op.calls), int(op.compiles)


# -- KVStore family (ref: MXKVStore* c_api.h; src/kvstore/kvstore.cc:40) ----

def kv_create(kind):
    import mxnet_tpu as mx
    return mx.kv.create(kind)


def kv_init(kv, key, arr):
    kv.init(key, arr)


def kv_push(kv, key, arr, priority):
    kv.push(key, arr, priority=priority)


def kv_pull(kv, key, out, priority):
    kv.pull(key, out=out, priority=priority)


def kv_pushpull(kv, key, arr, out, priority):
    kv.pushpull(key, arr, out=out, priority=priority)


def kv_rank(kv):
    return int(kv.rank)


def kv_size(kv):
    return int(kv.num_workers)


def kv_type(kv):
    return str(kv.type)


def kv_barrier(kv):
    """Global barrier across workers (ref: MXKVStoreBarrier)."""
    kv._barrier()


def kv_set_optimizer(kv, name, keys, vals):
    import mxnet_tpu.optimizer as opt
    params = {k: _parse(v) for k, v in zip(keys, vals)}
    kv.set_optimizer(opt.create(name, **params))


# -- DataIter family (ref: MXDataIter* c_api.h; src/io/io.cc registry) ------

_ITER_NAMES = ("MNISTIter", "CSVIter", "LibSVMIter", "ImageRecordIter")


def list_data_iters():
    return list(_ITER_NAMES)


class _IterCursor:
    """Holds the current batch so GetData/GetLabel have stable handles
    (the reference iterator's current DataBatch)."""

    def __init__(self, it):
        self.it = it
        self.batch = None


def data_iter_create(name, keys, vals):
    import mxnet_tpu.io as io
    import mxnet_tpu.image as image
    params = {k: _parse(v) for k, v in zip(keys, vals)}
    if name == "ImageRecordIter":
        from .io.image_iter import ImageRecordIter
        return _IterCursor(ImageRecordIter(**params))
    cls = getattr(io, name, None)
    if cls is None:
        cls = getattr(image, name, None)
    if cls is None:
        raise ValueError("unknown data iterator %r (have: %s)"
                         % (name, ", ".join(_ITER_NAMES)))
    return _IterCursor(cls(**params))


def data_iter_next(cur):
    try:
        cur.batch = cur.it.next()
        return 1
    except StopIteration:
        cur.batch = None
        return 0


def data_iter_data(cur):
    if cur.batch is None:
        raise RuntimeError("no current batch (call MXTDataIterNext first)")
    return cur.batch.data[0]


def data_iter_label(cur):
    if cur.batch is None:
        raise RuntimeError("no current batch (call MXTDataIterNext first)")
    return cur.batch.label[0]


def data_iter_reset(cur):
    cur.it.reset()
    cur.batch = None


# -- NDArray save/load (ref: MXNDArraySave/Load c_api.h:638-672) ------------

def nd_save(fname, arrays, names):
    import mxnet_tpu as mx
    if names:
        mx.nd.save(fname, dict(zip(names, arrays)))
    else:
        mx.nd.save(fname, list(arrays))


def nd_load(fname):
    import mxnet_tpu as mx
    data = mx.nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return names, [data[n] for n in names]
    return [], list(data)


def set_data(dst, src):
    """Device-side value copy dst <- src, no host round trip
    (ref: MXNDArraySyncCopyFromNDArray c_api.h)."""
    import jax.numpy as jnp
    if tuple(dst.shape) != tuple(src.shape):
        raise ValueError("MXTNDArrayCopyFrom: shape mismatch (dst %s, "
                         "src %s)" % (tuple(dst.shape), tuple(src.shape)))
    dst._data = jnp.asarray(src._data, dst._data.dtype)


def copy_from_bytes(arr, raw):
    """In-place value update (ref: MXNDArraySyncCopyFromCPU c_api.h:456)."""
    import jax.numpy as jnp
    new = np.frombuffer(raw, str(arr.dtype)).reshape(arr.shape)
    arr._data = jnp.asarray(np.ascontiguousarray(new))


# -- misc (seed/op list/lib loading) ----------------------------------------
# (the version constant lives C-side in MXTGetVersion, c_api_symbol.cc)

def random_seed(seed):
    import mxnet_tpu as mx
    mx.random.seed(int(seed))


def list_all_ops():
    from .ops import registry as _registry
    return sorted(set(_registry.list_ops()))


def load_lib(path):
    from . import lib_api
    lib_api.load(path)


# -- NDArray views (ref: MXNDArrayReshape/Slice/At c_api.h) -----------------

def nd_reshape(arr, shape):
    return arr.reshape(tuple(int(d) for d in shape))


def nd_slice(arr, begin, end):
    # the slice op takes per-axis tuples (ref: slice-inl.h SliceParam)
    return arr.slice((int(begin),), (int(end),))


def nd_at(arr, idx):
    return arr[int(idx)]


# -- autograd flags (ref: MXAutogradIsRecording/IsTraining/SetIsTraining) ---

def autograd_is_recording():
    return 1 if autograd.is_recording() else 0


def autograd_is_training():
    return 1 if autograd.is_training() else 0


def autograd_set_training(flag):
    autograd.set_training(bool(flag))


# -- profiler controls (ref: MXSetProcessProfilerConfig/State, MXDumpProfile)

def profiler_set_config(keys, vals):
    from . import profiler
    kwargs = {}
    for k, v in zip(keys, vals):
        kwargs[k] = _parse(v)
    profiler.set_config(**kwargs)


def profiler_set_state(state):
    from . import profiler
    profiler.set_state("run" if int(state) else "stop")


def profiler_dump():
    from . import profiler
    profiler.dump()


# -- Symbol attributes / views (ref: MXSymbolGetAttr/SetAttr/ListAttr,
#    MXSymbolGetInternals/GetOutput c_api.h) --------------------------------

def symbol_attr(sym, key):
    v = sym.attr(key)
    # None = missing; any string (even "") = present — the C side maps
    # this onto the (out, success) pair like the reference
    return None if v is None else str(v)


def symbol_set_attr(sym, key, val):
    # store the RAW string (ref: MXSymbolSetAttr keeps values verbatim;
    # a parse/re-stringify round trip would mutate "1.50" -> "1.5")
    sym._set_attr(**{key: val})


def symbol_attr_json(sym):
    import json as _json
    return _json.dumps(sym.attr_dict)


def symbol_get_internals(sym):
    return sym.get_internals()


def symbol_get_output(sym, index):
    return sym[int(index)]


def symbol_copy(sym):
    import copy as _copy
    return _copy.deepcopy(sym)


# -- round-4 ABI long tail (VERDICT r3 item 3: parity audit closures) -------

def nd_wait(arr):
    """MXTNDArrayWaitToRead/WaitToWrite core — per-array sync
    (ref: c_api.h MXNDArrayWaitToRead; XLA analog is
    block_until_ready)."""
    arr.wait_to_read()


def nd_detach(arr):
    return arr.detach()


_DEV_TYPE_IDS = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 2}


def nd_context(arr):
    """(dev_type_id, dev_id); accelerators report the reference's GPU id
    (2) — the ABI has no TPU enum and callers only branch cpu/非cpu."""
    ctx = arr.context
    return _DEV_TYPE_IDS.get(ctx.device_type, 2), int(ctx.device_id)


_STYPE_IDS = {"undefined": -1, "default": 0, "row_sparse": 1, "csr": 2}


def nd_storage_type(arr):
    return _STYPE_IDS.get(getattr(arr, "stype", "default"), 0)


def nd_none():
    """MXTNDArrayCreateNone: a placeholder handle
    (ref: c_api.cc MXNDArrayCreateNone)."""
    return NDArray(np.zeros((), "float32"))


def nd_shallow_copy(arr):
    return NDArray(arr._data)


def nd_load_from_buffer(raw):
    """Returns (names list, arrays list) like ndarray_load."""
    import os
    import tempfile
    fd, path = tempfile.mkstemp(suffix=".params")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        import mxnet_tpu as mx
        loaded = mx.nd.load(path)
    finally:
        os.unlink(path)
    if isinstance(loaded, dict):
        return list(loaded.keys()), list(loaded.values())
    return [], list(loaded)


def symbol_group(syms):
    from .symbol import Group
    return Group(list(syms))


def symbol_num_outputs(sym):
    return len(sym.list_outputs())


def symbol_print(sym):
    """Debug string (ref: MXSymbolPrint): name, args, outputs."""
    return ("Symbol(name=%s)\nArguments: %s\nOutputs: %s"
            % (sym.name, ", ".join(sym.list_arguments()),
               ", ".join(sym.list_outputs())))


def symbol_get_children(sym):
    kids = sym.get_children()
    return kids  # may be None; C side maps to null handle


def symbol_get_inputs(sym):
    from .symbol import Symbol
    nodes = [n for n in sym._topo() if n.is_variable()]
    return [Symbol([(n, 0)]) for n in nodes]


def symbol_atomic_name(sym):
    node = sym._outputs[0][0]
    return node.op or "null"


def symbol_attrs_shallow(sym):
    """Flat [k0, v0, k1, v1, ...] of the head node's own attrs."""
    out = []
    for k, v in sym._outputs[0][0].attrs.items():
        if not k.startswith("__"):
            out.extend([str(k), str(v)])
    return out


def symbol_infer_shape_partial(sym, names, shapes):
    provided = {n: tuple(s) for n, s in zip(names, shapes)}
    arg, out, aux = sym.infer_shape_partial(**provided)

    def _clean(lst):
        return [tuple(int(d) for d in s) if s is not None else ()
                for s in lst]
    return _clean(arg), _clean(out), _clean(aux)


def symbol_infer_type(sym, names, dtype_ids, partial):
    typed = {n: _DTYPES[int(d)] for n, d in zip(names, dtype_ids)}
    arg_t, out_t, aux_t = sym.infer_type(**typed)

    def ids(lst):
        return [(-1 if t is None else _DTYPE_IDS.get(str(np.dtype(t)), 0))
                for t in lst]
    return ids(arg_t), ids(out_t), ids(aux_t)


def executor_print(ex):
    args = {n: tuple(a.shape) for n, a in ex.arg_dict.items()}
    return "Executor(outputs=%d)\n%s" % (
        len(ex.outputs), "\n".join("  %s: %s" % kv for kv in args.items()))


def executor_reshape(ex, names, shapes):
    return ex.reshape(partial_shaping=True,
                      **{n: tuple(s) for n, s in zip(names, shapes)})


def executor_bind(sym, names, arrs, grad_req):
    from .executor import Executor
    args = dict(zip(names, arrs))
    grads = {n: NDArray(np.zeros(a.shape, str(a.dtype)))
             for n, a in args.items()}
    return Executor(sym, args=args, args_grad=grads, grad_req=grad_req)


def kv_role(which):
    """worker/server/scheduler booleans from the DMLC-compatible env
    (ref: MXKVStoreIsWorkerNode; every process is a worker here unless a
    reference-era launcher says otherwise)."""
    import os
    role = _getenv("DMLC_ROLE", "worker")
    return 1 if role == which else 0


def kv_num_dead(kv, node_id):
    get = getattr(kv, "get_dead_nodes", None)
    return len(get()) if get else 0


def kv_set_gradient_compression(kv, keys, vals):
    kv.set_gradient_compression(
        {k: _parse(v) for k, v in zip(keys, vals)})


def kv_pull_row_sparse(kv, key, row_ids, out):
    kv.row_sparse_pull(_parse_key(key), out=out, row_ids=row_ids)


def _parse_key(key):
    try:
        return int(key)
    except (TypeError, ValueError):
        return key


def notify_shutdown():
    import mxnet_tpu as mx
    mx.nd.waitall()


def init_ps_env(keys, vals):
    import os
    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)


# profiler object family (ref: MXProfileCreateDomain..SetMarker)

def profile_create(kind, domain, name):
    from . import profiler as prof
    if kind == "domain":
        return prof.Domain(name)
    klass = {"task": prof.Task, "frame": prof.Frame,
             "event": prof.Event, "counter": prof.Counter}[kind]
    if kind == "event":
        return prof.Event(name)
    return klass(domain, name)


def profile_duration(handle, start):
    handle.start() if start else handle.stop()


def profile_counter_set(handle, value):
    handle.set_value(value) if hasattr(handle, "set_value") else \
        setattr(handle, "value", value)


def profile_counter_adjust(handle, delta):
    if hasattr(handle, "increment"):
        handle.increment(delta)
    else:
        handle.value = getattr(handle, "value", 0) + delta


def profile_set_marker(domain, name, scope):
    from . import profiler as prof
    prof.Marker(domain, name).mark(scope or "process")


def profile_pause(paused):
    from . import profiler as prof
    prof.pause() if paused else prof.resume()


def profile_aggregate_stats(reset, format_, sort_by, ascending):
    from . import profiler as prof
    return prof.dumps(reset=bool(reset), format=format_ or "table",
                      sort_by=sort_by or "total",
                      ascending=bool(ascending))


def engine_set_bulk_size(size):
    """MXEngineSetBulkSize parity: sets the bulk segment cap and returns
    the previous value as an int. Setting the size is a segment boundary —
    any bulk segment pending on this thread is flushed first."""
    from . import engine
    return int(engine.set_bulk_size(int(size)))


def lib_info_features():
    """Flat [name, '1'/'0', ...] pairs (ref: MXLibInfoFeatures)."""
    from .runtime import Features
    out = []
    for name, feat in Features().items():
        out.extend([str(name), "1" if feat.enabled else "0"])
    return out


def np_shape_is():
    from . import util
    return 1 if util.is_np_shape() else 0


def np_shape_set(active):
    from . import util
    return 1 if util.set_np_shape(bool(active)) else 0


def device_count():
    """Accelerator count (ref: MXGetGPUCount) — CPU devices excluded so
    a CPU-only host reports 0, like the reference without GPUs."""
    import jax
    return sum(1 for d in jax.devices() if d.platform != "cpu")


def device_memory_info(dev_id):
    """(free, total) bytes; accelerator stats via PJRT when exposed."""
    import jax
    d = jax.devices()[int(dev_id)]
    stats = getattr(d, "memory_stats", lambda: None)() or {}
    total = int(stats.get("bytes_limit", 0))
    used = int(stats.get("bytes_in_use", 0))
    return total - used, total


def dataiter_index(it):
    batch = getattr(it, "batch", None)  # _IterCursor.batch
    idx = getattr(batch, "index", None) if batch is not None else None
    return [int(i) for i in idx] if idx is not None else []


def dataiter_pad(it):
    batch = getattr(it, "batch", None)  # _IterCursor.batch
    return int(getattr(batch, "pad", 0) or 0) if batch is not None else 0


def storage_empty_cache():
    from . import storage
    storage.empty_cache()
