"""``mxnet_tpu.nd`` — the imperative NDArray API namespace.

Mirrors the reference's ``mx.nd`` module layout
(ref: python/mxnet/ndarray/__init__.py): the NDArray class, creation
functions, and one generated wrapper per registered operator.
"""
from __future__ import annotations

import pickle
import struct

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import atomic_write as _atomic_write
from ..base import canonical_dtype
from ..context import current_context, Context
from .._debug import faultpoint as _faultpoint
from .._debug import memwatch as _memwatch
from .. import profiler as _profiler
from .. import storage as _storage
from .ndarray import NDArray, array, concatenate
from . import register as _register_mod

__all__ = ["NDArray", "array", "concatenate", "zeros", "ones", "full",
           "empty", "arange", "eye", "linspace", "waitall", "save", "load",
           "imperative_invoke"]


# -- creation ---------------------------------------------------------------

def _ctx_place(data, ctx):
    """Creation-factory device placement with a host-backed degradation
    path: a failed device_put (unknown ctx, backend OOM, or an injected
    ``storage.alloc`` fault) yields a host-resident NDArray with the
    same values instead of crashing — counted so the degradation is
    visible (``metrics()['memory']['alloc_fallbacks']``, the section's
    single owner), and written up as an OOM post-mortem flight-record
    shard naming the failed request size and what was resident
    (``_debug.memwatch.oom_report``)."""
    ctx = ctx or current_context()
    try:
        if _faultpoint.ACTIVE:
            _faultpoint.check("storage.alloc")
        placed = jax.device_put(data, ctx.jax_device())
        _storage.ledger_register(placed, "other")
        return NDArray(placed, ctx=ctx)
    except Exception as e:
        # counted with profiling off too (the account contract) — and
        # the memory section of metrics() is the one owner of
        # allocation accounting (ISSUE 13 satellite)
        _storage.bump("alloc_fallbacks")
        # only genuine memory exhaustion (or an injected storage.alloc
        # chaos fault, whose message names the point) writes the 'oom'
        # shard — an unknown-ctx TypeError in a loop must not mislabel
        # post-mortems or burn the dump cap
        if _memwatch.is_oom(e) or "storage.alloc" in str(e):
            try:
                nbytes = int(getattr(data, "nbytes", 0))
            except Exception:
                nbytes = None
            _memwatch.oom_report(e, requested_bytes=nbytes,
                                 where="storage.alloc")
        return NDArray(data, ctx=ctx)


def zeros(shape, ctx=None, dtype=None, stype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    # mxlint: disable=MX001 (creation factory: no tensor inputs for the cache/tape to key on; the ctx= device-placement contract is not expressible through the registry path)
    return _ctx_place(jnp.zeros(shape, canonical_dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    # mxlint: disable=MX001 (creation factory: no tensor inputs for the cache/tape to key on; the ctx= device-placement contract is not expressible through the registry path)
    return _ctx_place(jnp.ones(shape, canonical_dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    # mxlint: disable=MX001 (creation factory: no tensor inputs for the cache/tape to key on; the ctx= device-placement contract is not expressible through the registry path)
    return _ctx_place(jnp.full(shape, val, canonical_dtype(dtype)), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    # mxlint: disable=MX001 (creation factory: no tensor inputs for the cache/tape to key on; the ctx= device-placement contract is not expressible through the registry path)
    out = jnp.arange(start, stop, step, canonical_dtype(dtype))
    if repeat > 1:
        # mxlint: disable=MX001 (part of the arange creation factory above)
        out = jnp.repeat(out, repeat)
    return _ctx_place(out, ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None):
    # mxlint: disable=MX001 (creation factory: no tensor inputs for the cache/tape to key on; the ctx= device-placement contract is not expressible through the registry path)
    return _ctx_place(jnp.eye(N, M if M else None, k, canonical_dtype(dtype)), ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    # mxlint: disable=MX001 (creation factory: no tensor inputs for the cache/tape to key on; the ctx= device-placement contract is not expressible through the registry path)
    return _ctx_place(jnp.linspace(start, stop, num, endpoint=endpoint,
                                   dtype=canonical_dtype(dtype)), ctx)


def waitall():
    """ref: mx.nd.waitall → Engine::WaitForAll. Drains any pending bulk
    segment (queued imperative ops run now; their errors surface here, the
    sync point), then a tiny device fence — XLA async dispatch drains when
    we block on effects."""
    from .. import engine as _engine
    _engine._flush_pending_segment()
    try:
        # mxlint: disable=MX001 (zero-size device fence, not an op dispatch)
        jax.block_until_ready(jnp.zeros(()))
    except Exception:
        pass


def imperative_invoke(name, *args, **kwargs):
    return _register_mod.invoke_by_name(name, *args, **kwargs)


# -- serialization (ref: MXNDArraySave/Load, include/mxnet/c_api.h:638-672) --

_MAGIC = b"MXTPU_ND1"


# Reference binary .params format, byte-identical to MXNDArraySave
# (ref: src/ndarray/ndarray.cc:1829 NDArray::Save list writer, :1603 the
# per-array V2 record; include/mxnet/tuple.h:704 TShape::Save;
# include/mxnet/base.h:157 Context::Save). Checkpoints written by the
# reference load here unchanged and vice versa.
_LIST_MAGIC = 0x112
_ND_V2_MAGIC = 0xF993fac9
_ND_V3_MAGIC = 0xF993faca  # np-shape semantics; same layout
_TYPE_FLAGS = {  # mshadow type_flag <-> numpy dtype
    0: _np.dtype("float32"), 1: _np.dtype("float64"),
    2: _np.dtype("float16"), 3: _np.dtype("uint8"),
    4: _np.dtype("int32"), 5: _np.dtype("int8"), 6: _np.dtype("int64"),
    7: _np.dtype("bool"),
}
_DTYPE_TO_FLAG = {v: k for k, v in _TYPE_FLAGS.items()}


def _write_one(f, arr):
    a = arr.asnumpy() if isinstance(arr, NDArray) else _np.asarray(arr)
    if a.dtype not in _DTYPE_TO_FLAG:
        if str(a.dtype) == "bfloat16":
            # bf16 has no 1.x type flag; store as f32 so reference tools
            # can read the checkpoint
            a = a.astype(_np.float32)
        else:
            raise TypeError("dtype %s has no reference type flag; cast "
                            "before saving" % a.dtype)
    # 0-dim arrays need V3 (np-shape) records: under legacy V2 semantics
    # ndim==0 means "unknown shape" (ref: ndarray.cc:1600 V3 comment)
    f.write(struct.pack("<I", _ND_V3_MAGIC if a.ndim == 0
                        else _ND_V2_MAGIC))
    f.write(struct.pack("<i", 0))                      # kDefaultStorage
    f.write(struct.pack("<i", a.ndim))
    f.write(struct.pack("<%dq" % a.ndim, *a.shape))
    f.write(struct.pack("<ii", 1, 0))                  # Context: cpu(0)
    f.write(struct.pack("<i", _DTYPE_TO_FLAG[a.dtype]))
    f.write(_np.ascontiguousarray(a).tobytes())


def _read_one(f):
    magic, = struct.unpack("<I", f.read(4))
    if magic not in (_ND_V2_MAGIC, _ND_V3_MAGIC):
        raise ValueError("unsupported NDArray record magic 0x%x (V1 legacy "
                         "files are not supported)" % magic)
    stype, = struct.unpack("<i", f.read(4))
    if stype != 0:
        raise ValueError("only dense (default storage) records are "
                         "supported, got stype=%d" % stype)
    ndim, = struct.unpack("<i", f.read(4))
    shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) if ndim else ()
    struct.unpack("<ii", f.read(8))                    # context, ignored
    type_flag, = struct.unpack("<i", f.read(4))
    dtype = _TYPE_FLAGS.get(type_flag)
    if dtype is None:
        raise ValueError("NDArray record has unsupported mshadow type "
                         "flag %d" % type_flag)
    count = int(_np.prod(shape)) if shape else 1
    data = _np.frombuffer(f.read(count * dtype.itemsize), dtype=dtype)
    return array(data.reshape(shape), dtype=str(dtype))


def save(fname, data):
    """Save NDArrays in the reference's .params binary format
    (ref: python/mxnet/ndarray/utils.py save → MXNDArraySave).

    Crash-consistent: written to a temp sibling and atomically renamed
    (base.atomic_write), so an interrupted save — process kill, full
    disk, injected ``checkpoint.save`` fault — never corrupts an
    existing checkpoint at ``fname``."""
    if isinstance(data, NDArray):
        arrays, names = [data], []
    elif isinstance(data, (list, tuple)):
        if any(not isinstance(a, NDArray) for a in data):
            raise TypeError("save expects NDArrays")
        arrays, names = list(data), []
    elif isinstance(data, dict):
        names = sorted(data)
        arrays = [data[k] for k in names]
    else:
        raise TypeError("unsupported save payload %r" % type(data))
    with _atomic_write(fname) as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_one(f, a)
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def _load_stream(f, where="<stream>"):
    head = f.read(len(_MAGIC))
    if head == _MAGIC:  # early-round pickle snapshot
        kind, payload = pickle.load(f)
        if kind == "single":
            return array(payload)
        if kind == "list":
            return [array(a) for a in payload]
        return {k: array(v) for k, v in payload.items()}
    f.seek(0)
    try:
        header, reserved = struct.unpack("<QQ", f.read(16))
        if header != _LIST_MAGIC:
            raise ValueError("not an NDArray file: %s" % where)
        count, = struct.unpack("<Q", f.read(8))
        arrays = [_read_one(f) for _ in range(count)]
        nnames, = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(nnames):
            ln, = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    except struct.error:
        raise ValueError("truncated or corrupt NDArray file: %s" % where)
    if count == 0:
        # ambiguous on disk; dict is what every param-dict consumer
        # (load_parameters, load_checkpoint) expects from an empty save
        return {}
    if not names:
        return arrays
    if len(names) != len(arrays):
        raise ValueError("invalid NDArray file (%d names for %d arrays): %s"
                         % (len(names), len(arrays), where))
    return dict(zip(names, arrays))


def load(fname):
    """Load a .params file or file-like object (reference binary format,
    plus this framework's earlier pickle snapshots for back compatibility).
    Like the reference's mx.nd.load: a list when records are unnamed, a
    dict otherwise (and for empty files)."""
    if hasattr(fname, "read"):
        return _load_stream(fname)
    with open(fname, "rb") as f:
        return _load_stream(f, where=fname)


# -- dynamic-shape ops (eager-only; ref: SURVEY.md §7 hard part (b)) --------

def boolean_mask(data, index, axis=0):
    """Select slices where index is nonzero (ref:
    src/operator/contrib/boolean_mask.cc). Output shape is data-dependent,
    so this is an EAGER op — inside jit/hybridize use `where` with a mask
    (static shape) or pad like BucketingModule. Differentiable in data
    (scatter-back gradient, like the reference's backward)."""
    from .. import autograd as _autograd
    m = index._data if isinstance(index, NDArray) else jnp.asarray(index)
    keep = jnp.asarray(_np.nonzero(_np.asarray(m) != 0)[0])

    def fwd(x):
        # mxlint: disable=MX001 (indexing internal: gather by host-computed positions; the registry path would re-enter __getitem__)
        return jnp.take(x, keep, axis=axis)

    if isinstance(data, NDArray) and _autograd.is_recording():
        out, vjp_fn = jax.vjp(fwd, data._data)
        res = NDArray(out)
        node = _autograd.record_op("boolean_mask", [res], [data], vjp_fn)
        node.fwd_fn = fwd
        return res
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    return NDArray(fwd(d))


def unique(data):
    """Sorted unique values (eager; dynamic output shape)."""
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    return NDArray(jnp.asarray(_np.unique(_np.asarray(d))))


# -- generated op wrappers --------------------------------------------------
_register_mod.populate(globals())

# submodule-style namespaces (mx.nd.random, mx.nd.linalg, mx.nd.image)
from . import random   # noqa: E402,F401
from . import linalg   # noqa: E402,F401
from . import sparse   # noqa: E402,F401
from . import image    # noqa: E402,F401

# top-level aliases matching the reference namespace (mx.nd.cast_storage
# in addition to mx.nd.sparse.cast_storage)
cast_storage = sparse.cast_storage
sparse_retain = sparse.retain

# sparse-aware dot: CSR operands take the device-native kernel
# (ref: dot-inl.h DotCsrDnsDns); dense operands keep the registry path
_dense_dot = globals()["dot"]


def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None, **kw):
    from .sparse import CSRNDArray
    if isinstance(lhs, CSRNDArray) or isinstance(rhs, CSRNDArray):
        if kw:
            raise TypeError("unsupported kwargs for sparse dot: %s"
                            % sorted(kw))
        res = sparse.dot(lhs, rhs, transpose_a=transpose_a,
                         transpose_b=transpose_b)
        if out is not None:
            out._data = res._data
            return out
        return res
    return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b, out=out, **kw)
from . import contrib  # noqa: E402,F401

# fused optimizer update ops with the reference's in-place calling
# convention (mom/mean/var states mutated, out= delivery) — these override
# any generated wrappers of the same name
from .optimizer_ops import *  # noqa: E402,F401,F403


def Custom(*args, **kwargs):
    """Run a registered Python custom op
    (ref: python/mxnet/operator.py register + nd.Custom)."""
    from ..operator import invoke as _custom_invoke
    return _custom_invoke(*args, **kwargs)
