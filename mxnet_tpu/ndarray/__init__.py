"""``mxnet_tpu.nd`` — the imperative NDArray API namespace.

Mirrors the reference's ``mx.nd`` module layout
(ref: python/mxnet/ndarray/__init__.py): the NDArray class, creation
functions, and one generated wrapper per registered operator.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import canonical_dtype
from ..context import current_context, Context
from .ndarray import NDArray, array, concatenate
from . import register as _register_mod

__all__ = ["NDArray", "array", "concatenate", "zeros", "ones", "full",
           "empty", "arange", "eye", "linspace", "waitall", "save", "load",
           "imperative_invoke"]


# -- creation ---------------------------------------------------------------

def _ctx_place(data, ctx):
    ctx = ctx or current_context()
    try:
        return NDArray(jax.device_put(data, ctx.jax_device()), ctx=ctx)
    except Exception:
        return NDArray(data, ctx=ctx)


def zeros(shape, ctx=None, dtype=None, stype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _ctx_place(jnp.zeros(shape, canonical_dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _ctx_place(jnp.ones(shape, canonical_dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _ctx_place(jnp.full(shape, val, canonical_dtype(dtype)), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    out = jnp.arange(start, stop, step, canonical_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return _ctx_place(out, ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None):
    return _ctx_place(jnp.eye(N, M if M else None, k, canonical_dtype(dtype)), ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    return _ctx_place(jnp.linspace(start, stop, num, endpoint=endpoint,
                                   dtype=canonical_dtype(dtype)), ctx)


def waitall():
    """ref: mx.nd.waitall → Engine::WaitForAll. XLA async dispatch drains when
    we block on effects; jax exposes no global barrier, so this is a no-op
    fence plus a tiny device sync."""
    try:
        jax.block_until_ready(jnp.zeros(()))
    except Exception:
        pass


def imperative_invoke(name, *args, **kwargs):
    return _register_mod.invoke_by_name(name, *args, **kwargs)


# -- serialization (ref: MXNDArraySave/Load, include/mxnet/c_api.h:638-672) --

_MAGIC = b"MXTPU_ND1"


def save(fname, data):
    """Save an NDArray, list of NDArrays, or dict str->NDArray."""
    if isinstance(data, NDArray):
        payload = ("single", _np.asarray(data.asnumpy()))
    elif isinstance(data, (list, tuple)):
        payload = ("list", [_np.asarray(a.asnumpy()) for a in data])
    elif isinstance(data, dict):
        payload = ("dict", {k: _np.asarray(v.asnumpy()) for k, v in data.items()})
    else:
        raise TypeError("unsupported save payload %r" % type(data))
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(payload, f, protocol=4)


def load(fname):
    with open(fname, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError("not a %s NDArray file: %s" % ("mxnet_tpu", fname))
        kind, payload = pickle.load(f)
    if kind == "single":
        return array(payload)
    if kind == "list":
        return [array(a) for a in payload]
    return {k: array(v) for k, v in payload.items()}


# -- generated op wrappers --------------------------------------------------
_register_mod.populate(globals())

# submodule-style namespaces (mx.nd.random, mx.nd.linalg)
from . import random   # noqa: E402,F401
from . import linalg   # noqa: E402,F401
from . import sparse   # noqa: E402,F401
