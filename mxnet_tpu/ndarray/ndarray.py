"""NDArray: imperative tensor with MXNet semantics on a jax.Array.

TPU-native re-design of the reference NDArray
(ref: include/mxnet/ndarray.h:82, src/ndarray/ndarray.cc,
python/mxnet/ndarray/ndarray.py). Differences by design:

- The reference pairs every array with a dependency-engine variable and
  schedules kernels through the threaded engine (ref: src/engine/). JAX's
  async dispatch gives the same ops-return-immediately behaviour, so
  ``wait_to_read`` maps to ``jax.block_until_ready`` and there is no engine to
  re-implement.
- Mutation (``x += 1``, ``x[1:3] = v``) is implemented by functional update:
  the wrapper swaps the underlying immutable buffer. Version semantics match
  the reference's write-dependency ordering because Python program order is
  the only ordering eager code can observe.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import canonical_dtype
from ..context import Context, current_context
from .. import autograd
from .. import storage as _storage

__all__ = ["NDArray", "array", "concatenate"]


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


class _PendingSlot:
    """Placeholder buffer for an NDArray produced by a queued bulk-segment
    op (engine.bulk — the imperative CachedOp seam). Shape/dtype are known
    from abstract evaluation; the concrete ``jax.Array`` materialises when
    the owning segment flushes. Reading ``NDArray._data`` is a sync point:
    the property getter flushes the segment transparently."""

    __slots__ = ("segment", "shape", "dtype", "ndim", "ref")

    def __init__(self, segment, shape, dtype, ref):
        self.segment = segment
        self.shape = tuple(shape)
        self.dtype = _np.dtype(dtype)
        self.ndim = len(self.shape)
        self.ref = ref  # ("o", op_idx, out_idx) within the segment


class NDArray:
    """N-dimensional array on a device context."""

    __slots__ = ("_buf", "_ctx", "_grad", "_grad_req", "_autograd_entry",
                 "_deferred_init", "_fresh_grad", "__weakref__")

    def __init__(self, data, ctx=None):
        self._buf = data
        self._ctx = ctx
        self._grad = None
        self._grad_req = "null"
        self._autograd_entry = None

    # -- buffer access (the engine sync point) ----------------------------
    @property
    def _data(self):
        """The concrete jax.Array. If the buffer is still pending inside a
        bulk segment, reading it flushes the segment first — the analog of
        the reference engine's WaitToRead dependency resolution."""
        buf = self._buf
        if type(buf) is _PendingSlot:
            buf.segment.flush()
            buf = self._buf
            if type(buf) is _PendingSlot:
                raise RuntimeError(
                    "NDArray depends on a bulk-segment op that failed; "
                    "the original error was raised at the flush point")
        return buf

    @_data.setter
    def _data(self, value):
        self._buf = value

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._buf.shape)

    @property
    def ndim(self):
        return self._buf.ndim

    @property
    def size(self):
        return int(_np.prod(self.shape)) if self.shape else 1

    @property
    def dtype(self):
        return _np.dtype(self._buf.dtype)

    @property
    def nbytes(self):
        return self.size * self.dtype.itemsize

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        if _is_tracer(self._data):
            return current_context()
        try:
            dev = list(self._data.devices())[0]
            if dev.platform == "cpu":
                return Context("cpu", dev.id)
            return Context("tpu", dev.id)
        except Exception:
            return current_context()

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        from . import transpose
        return transpose(self)

    # -- sync / host transfer --------------------------------------------
    def wait_to_read(self):
        """ref: NDArray::WaitToRead (include/mxnet/ndarray.h) — block until
        all pending async work producing this array is done."""
        jax.block_until_ready(self._data)
        return self

    def wait_to_write(self):
        jax.block_until_ready(self._data)
        return self

    def asnumpy(self):
        return _np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        if _is_tracer(self._data):
            return "\n<%s %s @%s (traced)>" % (
                type(self).__name__, "x".join(map(str, self.shape)), self.context)
        return "\n%s\n<%s %s @%s>" % (
            str(self.asnumpy()), type(self).__name__,
            "x".join(map(str, self.shape)), self.context)

    # -- conversion / copy ------------------------------------------------
    def astype(self, dtype, copy=True):
        from . import cast
        return cast(self, dtype=_np.dtype(canonical_dtype(dtype)).name
                    if not isinstance(dtype, str) else dtype)

    def copy(self):
        # buffers are immutable; sharing is an O(1) copy with value semantics
        return NDArray(self._data, ctx=self._ctx)

    def copyto(self, other):
        """ref: python/mxnet/ndarray/ndarray.py copyto."""
        if isinstance(other, NDArray):
            other._data = _place(self._data, other.context)
            return other
        if isinstance(other, Context):
            return NDArray(_place(self._data, other), ctx=other)
        raise TypeError("copyto target must be NDArray or Context")

    def as_in_context(self, context):
        if context == self.context:
            return self
        return NDArray(_place(self._data, context), ctx=context)

    def as_in_ctx(self, ctx):
        return self.as_in_context(ctx)

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """ref: python/mxnet/ndarray/ndarray.py attach_grad."""
        # mxlint: disable=MX001 (grad-buffer alloc, not an op — must not hit the tape/cache)
        self._grad = NDArray(jnp.zeros(self.shape, self.dtype)) \
            if grad_req != "null" else None
        if self._grad is not None:
            _storage.ledger_register(self._grad._buf, "grad")
        self._grad_req = grad_req
        self._autograd_entry = None

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad], retain_graph=retain_graph,
                          train_mode=train_mode)

    # -- shape ops (method forms) ----------------------------------------
    def reshape(self, *shape, **kwargs):
        from . import reshape as _reshape
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape and "shape" in kwargs:
            shape = kwargs["shape"]
        return _reshape(self, shape=shape, reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        from . import expand_dims as _f
        return _f(self, axis=axis)

    def squeeze(self, axis=None):
        from . import squeeze as _f
        return _f(self, axis=axis)

    def transpose(self, *axes):
        from . import transpose as _f
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _f(self, axes=axes if axes else None)

    def swapaxes(self, dim1, dim2):
        from . import swapaxes as _f
        return _f(self, dim1=dim1, dim2=dim2)

    def flatten(self):
        from . import flatten as _f
        return _f(self)

    def flip(self, axis):
        from . import reverse as _f
        return _f(self, axis=axis)

    def tile(self, reps):
        from . import tile as _f
        return _f(self, reps=reps)

    def repeat(self, repeats, axis=None):
        from . import repeat as _f
        return _f(self, repeats=repeats, axis=axis)

    def broadcast_to(self, shape):
        from . import broadcast_to as _f
        return _f(self, shape=shape)

    def broadcast_like(self, other):
        from . import broadcast_like as _f
        return _f(self, other)

    def slice(self, begin, end, step=None):
        from . import slice as _f
        return _f(self, begin=begin, end=end, step=step or ())

    def slice_axis(self, axis, begin, end):
        from . import slice_axis as _f
        return _f(self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        from . import take as _f
        return _f(self, indices, axis=axis, mode=mode)

    def one_hot(self, depth, **kw):
        from . import one_hot as _f
        return _f(self, depth=depth, **kw)

    def pick(self, index, axis=-1, keepdims=False):
        from . import pick as _f
        return _f(self, index, axis=axis, keepdims=keepdims)

    def clip(self, a_min, a_max):
        from . import clip as _f
        return _f(self, a_min=a_min, a_max=a_max)

    def abs(self):
        from . import abs as _f
        return _f(self)

    def sign(self):
        from . import sign as _f
        return _f(self)

    def sqrt(self):
        from . import sqrt as _f
        return _f(self)

    def square(self):
        from . import square as _f
        return _f(self)

    def exp(self):
        from . import exp as _f
        return _f(self)

    def log(self):
        from . import log as _f
        return _f(self)

    def sigmoid(self):
        from . import sigmoid as _f
        return _f(self)

    def tanh(self):
        from . import tanh as _f
        return _f(self)

    def relu(self):
        from . import relu as _f
        return _f(self)

    def softmax(self, axis=-1):
        from . import softmax as _f
        return _f(self, axis=axis)

    def log_softmax(self, axis=-1):
        from . import log_softmax as _f
        return _f(self, axis=axis)

    def sum(self, axis=None, keepdims=False, **kw):
        from . import sum as _f
        return _f(self, axis=axis, keepdims=keepdims, **kw)

    def mean(self, axis=None, keepdims=False, **kw):
        from . import mean as _f
        return _f(self, axis=axis, keepdims=keepdims, **kw)

    def prod(self, axis=None, keepdims=False):
        from . import prod as _f
        return _f(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        from . import max as _f
        return _f(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        from . import min as _f
        return _f(self, axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        from . import norm as _f
        return _f(self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        from . import argmax as _f
        return _f(self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        from . import argmin as _f
        return _f(self, axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        from . import argsort as _f
        return _f(self, axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        from . import sort as _f
        return _f(self, axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        from . import topk as _f
        return _f(self, axis=axis, k=k, ret_typ=ret_typ, is_ascend=is_ascend)

    def dot(self, other, **kw):
        from . import dot as _f
        return _f(self, other, **kw)

    def zeros_like(self):
        # through the dispatch choke point: jit-cached, bulkable, and
        # visible to the profiler lane (mxlint MX001)
        from .register import invoke_by_name
        return invoke_by_name("zeros_like", self)

    def ones_like(self):
        from .register import invoke_by_name
        return invoke_by_name("ones_like", self)

    # -- arithmetic operators --------------------------------------------
    def _binop(self, name, other, reverse=False):
        a, b = (other, self) if reverse else (self, other)
        return _register_mod().invoke_by_name(name, a, b)

    def __add__(self, other):
        return self._binop("add", other)

    def __radd__(self, other):
        return self._binop("add", other, True)

    def __sub__(self, other):
        return self._binop("subtract", other)

    def __rsub__(self, other):
        return self._binop("subtract", other, True)

    def __mul__(self, other):
        return self._binop("multiply", other)

    def __rmul__(self, other):
        return self._binop("multiply", other, True)

    def __truediv__(self, other):
        return self._binop("divide", other)

    def __rtruediv__(self, other):
        return self._binop("divide", other, True)

    def __div__(self, other):
        return self._binop("divide", other)

    def __mod__(self, other):
        return self._binop("mod", other)

    def __rmod__(self, other):
        return self._binop("mod", other, True)

    def __pow__(self, other):
        return self._binop("power", other)

    def __rpow__(self, other):
        return self._binop("power", other, True)

    def __neg__(self):
        from . import negative as _f
        return _f(self)

    def __abs__(self):
        return self.abs()

    def __eq__(self, other):
        if other is None:
            return False
        return self._binop("equal", other)

    def __ne__(self, other):
        if other is None:
            return True
        return self._binop("not_equal", other)

    def __gt__(self, other):
        return self._binop("greater", other)

    def __ge__(self, other):
        return self._binop("greater_equal", other)

    def __lt__(self, other):
        return self._binop("lesser", other)

    def __le__(self, other):
        return self._binop("lesser_equal", other)

    __hash__ = object.__hash__

    # in-place (functional update under the hood)
    def _check_inplace(self):
        if autograd.is_recording() and self._autograd_entry is not None:
            raise RuntimeError(
                "in-place mutation of a recorded NDArray inside "
                "autograd.record() is not supported (matches reference "
                "restriction on arrays that need grad)")

    def __iadd__(self, other):
        self._check_inplace()
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data + o
        return self

    def __isub__(self, other):
        self._check_inplace()
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data - o
        return self

    def __imul__(self, other):
        self._check_inplace()
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data * o
        return self

    def __itruediv__(self, other):
        self._check_inplace()
        o = other._data if isinstance(other, NDArray) else other
        self._data = self._data / o
        return self

    # -- indexing ---------------------------------------------------------
    @staticmethod
    def _clean_index(key):
        if isinstance(key, NDArray):
            return key._data if _np.issubdtype(key.dtype, _np.bool_) \
                else key._data.astype(jnp.int32)
        if isinstance(key, tuple):
            return tuple(NDArray._clean_index(k) for k in key)
        if isinstance(key, list):
            return jnp.asarray(key)
        return key

    def __getitem__(self, key):
        return _register_mod().invoke_getitem(self, self._clean_index(key))

    def __setitem__(self, key, value):
        self._check_inplace()
        k = self._clean_index(key)
        v = value._data if isinstance(value, NDArray) else value
        if isinstance(v, _np.ndarray):
            v = jnp.asarray(v)
        self._data = self._data.at[k].set(v)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # np-array interop (ref: python/mxnet/ndarray/ndarray.py as_np_ndarray)
    def as_np_ndarray(self):
        from ..numpy.multiarray import ndarray as _np_ndarray
        return _np_ndarray._adopt(self)

    # numpy protocol
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    @property
    def dlpack(self):
        """Zero-copy interchange: jax arrays implement the standard
        ``__dlpack__`` protocol, so the buffer itself is the capsule
        carrier (ref: tests/python/unittest/test_dlpack.py;
        to_dlpack_for_read in python/mxnet/ndarray/ndarray.py)."""
        return self._data

    def __dlpack__(self, *args, **kwargs):
        return self._data.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()


_REGISTER_MOD = None


def _register_mod():
    """Lazy handle on .register (it imports this module; a top-level
    import here would cycle). Memoized: the per-op import-machinery cost
    (~2us) matters on the dispatch hot path."""
    global _REGISTER_MOD
    if _REGISTER_MOD is None:
        from . import register
        _REGISTER_MOD = register
    return _REGISTER_MOD


def _place(data, ctx):
    if _is_tracer(data):
        return data
    out = jax.device_put(data, ctx.jax_device())
    # allocation-ledger choke point (ISSUE 13a): every framework-side
    # device placement — array(), copyto, as_in_context — lands in the
    # tagged ledger; cheap no-op when the ledger/telemetry is off
    _storage.ledger_register(out, "other")
    return out


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like.
    ref: python/mxnet/ndarray/utils.py array()."""
    if isinstance(source_array, NDArray):
        data = source_array._data
    else:
        npv = _np.asarray(source_array,
                          dtype=canonical_dtype(dtype) if dtype is not None
                          else None)
        if npv.dtype == _np.float64 and dtype is None:
            # reference defaults to float32 (python/mxnet/ndarray/ndarray.py)
            npv = npv.astype(_np.float32)
        data = jnp.asarray(npv)
    ctx = ctx or current_context()
    return NDArray(_place(data, ctx) if not _is_tracer(data) else data, ctx=ctx)


def concatenate(arrays, axis=0, always_copy=True):
    from . import concat
    return concat(*arrays, dim=axis)
