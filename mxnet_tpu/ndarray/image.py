"""mx.nd.image — functional image op namespace.

ref: python/mxnet/ndarray/image.py (generated from the _image_* registry
names, src/operator/image/image_random.cc). Exposes each registered
``_image_X`` op as ``nd.image.X``.
"""
from __future__ import annotations

from ..ops import registry as _registry
from .register import make_op_func

__all__ = []


def _populate_image():
    g = globals()
    for name in _registry.list_ops():
        if name.startswith("_image_"):
            short = name[len("_image_"):]
            if short not in g:
                g[short] = make_op_func(_registry.get_op(name), short)
                __all__.append(short)


_populate_image()
