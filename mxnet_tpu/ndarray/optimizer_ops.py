"""Fused optimizer update ops with the reference's in-place semantics.

ref: src/operator/optimizer_op.cc registrations + kernels in
optimizer_op-inl.h (SGDKernel :382, SGDMomKernel :600, NAGMomKernel
:1060, AdamUpdateKernel :1302, RMSPropUpdateKernel :1717,
RMSPropAlexUpdateKernel :1619, FTRLKernel :1797, FTMLKernel :1214,
SignSGDKernel :1998, SignumKernel :2066) and
src/operator/contrib/adamw.cc, multi_lars.cc,
src/operator/optimizer_op.cc multi_sgd/preloaded variants.

These are the nd-level entry points (`mx.nd.sgd_update(w, g, out=w, ...)`)
that the reference's Python optimizers call into. State inputs (momentum,
mean/var, n/z/...) are updated IN PLACE on the passed NDArrays, and the
new weight is returned (written into ``out`` when given) — exactly the
reference's calling convention. The Python `mxnet_tpu.optimizer` classes
keep their own fused-jit path; these ops exist for direct-API parity.

On TPU each call XLA-dispatches a small fused program; for whole-step
fusion use ShardedTrainStep (parallel/train.py), which compiles the
forward+backward+update pipeline into one program instead.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ndarray import NDArray

__all__ = [
    "sgd_update", "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update",
    "nag_mom_update", "mp_nag_mom_update", "adam_update", "rmsprop_update",
    "rmspropalex_update", "ftrl_update", "ftml_update", "signsgd_update",
    "signum_update", "adamw_update", "mp_adamw_update",
    "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
    "multi_mp_sgd_mom_update", "preloaded_multi_sgd_update",
    "preloaded_multi_sgd_mom_update", "preloaded_multi_mp_sgd_update",
    "preloaded_multi_mp_sgd_mom_update", "multi_lars",
    "sparse_adagrad_update", "group_adagrad_update", "lamb_update_phase1",
    "lamb_update_phase2",
]


def _d(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _clip(g, c):
    return jnp.clip(g, -c, c) if c is not None and c >= 0 else g


def _deliver(out, new_w):
    if out is not None:
        out._data = new_w.astype(out._data.dtype)
        return out
    return NDArray(new_w)


def _scalar(v):
    return float(v) if not isinstance(v, NDArray) else _d(v)


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, out=None, **kw):
    """ref: optimizer_op-inl.h:382 SGDKernel."""
    w, g = _d(weight), _d(grad)
    g = _clip(rescale_grad * g, clip_gradient)
    new_w = (1.0 - lr * wd) * w - lr * g
    return _deliver(out, new_w)


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   out=None, **kw):
    """ref: optimizer_op-inl.h:600 SGDMomKernel (mom updated in place)."""
    w, g, m = _d(weight), _d(grad), _d(mom)
    g = _clip(rescale_grad * g, clip_gradient)
    new_m = momentum * m - lr * wd * w - lr * g
    mom._data = new_m.astype(mom._data.dtype)
    return _deliver(out, w + new_m)


def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True, out=None, **kw):
    """Multi-precision SGD: update fp32 master, cast down
    (ref: optimizer_op-inl.h MP_SGDKernel)."""
    w32, g = _d(weight32), _d(grad).astype(jnp.float32)
    g = _clip(rescale_grad * g, clip_gradient)
    new_w32 = (1.0 - lr * wd) * w32 - lr * g
    weight32._data = new_w32
    return _deliver(out if out is not None else weight,
                    new_w32.astype(_d(weight).dtype))


def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True, out=None, **kw):
    """ref: optimizer_op-inl.h MP_SGDMomKernel."""
    w32, g, m = _d(weight32), _d(grad).astype(jnp.float32), _d(mom)
    g = _clip(rescale_grad * g, clip_gradient)
    new_m = momentum * m - lr * wd * w32 - lr * g
    mom._data = new_m
    new_w32 = w32 + new_m
    weight32._data = new_w32
    return _deliver(out if out is not None else weight,
                    new_w32.astype(_d(weight).dtype))


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    """Nesterov momentum (ref: optimizer_op-inl.h:1060 NAGMomKernel)."""
    w, g, m = _d(weight), _d(grad), _d(mom)
    g = _clip(rescale_grad * g, clip_gradient) + wd * w
    m_scaled = momentum * m
    new_w = w - m_scaled + (momentum + 1.0) * (m_scaled - lr * g)
    mom._data = (m_scaled - lr * g).astype(mom._data.dtype)
    return _deliver(out, new_w)


def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      out=None, **kw):
    """ref: optimizer_op-inl.h MP_NAGMomKernel."""
    w32, g, m = _d(weight32), _d(grad).astype(jnp.float32), _d(mom)
    g = _clip(rescale_grad * g, clip_gradient) + wd * w32
    m_scaled = momentum * m
    new_w32 = w32 - m_scaled + (momentum + 1.0) * (m_scaled - lr * g)
    mom._data = m_scaled - lr * g
    weight32._data = new_w32
    return _deliver(out if out is not None else weight,
                    new_w32.astype(_d(weight).dtype))


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, out=None, **kw):
    """ref: optimizer_op-inl.h:1302 AdamUpdateKernel (no bias correction —
    the Python optimizer folds it into lr, like the reference)."""
    w, g = _d(weight), _d(grad)
    m, v = _d(mean), _d(var)
    g = _clip(g * rescale_grad + wd * w, clip_gradient)
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * g * g
    mean._data = new_m.astype(m.dtype)
    var._data = new_v.astype(v.dtype)
    return _deliver(out, w - lr * new_m / (jnp.sqrt(new_v) + epsilon))


def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0,
                   out=None, **kw):
    """ref: optimizer_op-inl.h:1717 RMSPropUpdateKernel."""
    w, g, sn = _d(weight), _d(grad), _d(n)
    g = _clip(rescale_grad * g + wd * w, clip_gradient)
    new_n = (1.0 - gamma1) * g * g + gamma1 * sn
    n._data = new_n.astype(sn.dtype)
    new_w = w - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return _deliver(out, new_w)


def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, out=None,
                       **kw):
    """Graves' RMSProp (ref: optimizer_op-inl.h:1619
    RMSPropAlexUpdateKernel)."""
    w, gr = _d(weight), _d(grad)
    sn, sg, sd = _d(n), _d(g), _d(delta)
    gr = _clip(rescale_grad * gr + wd * w, clip_gradient)
    new_n = (1.0 - gamma1) * gr * gr + gamma1 * sn
    new_g = (1.0 - gamma1) * gr + gamma1 * sg
    new_d = gamma2 * sd - lr * gr / jnp.sqrt(new_n - new_g * new_g
                                             + epsilon)
    n._data = new_n.astype(sn.dtype)
    g._data = new_g.astype(sg.dtype)
    delta._data = new_d.astype(sd.dtype)
    new_w = w + new_d
    if clip_weights is not None and clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return _deliver(out, new_w)


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op-inl.h:1797 FTRLKernel."""
    w, g = _d(weight), _d(grad)
    sz, sn = _d(z), _d(n)
    g = _clip(rescale_grad * g, clip_gradient)
    new_z = sz + g - (jnp.sqrt(sn + g * g) - jnp.sqrt(sn)) / lr * w
    new_n = sn + g * g
    z._data = new_z.astype(sz.dtype)
    n._data = new_n.astype(sn.dtype)
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(w),
        (jnp.sign(new_z) * lamda1 - new_z)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return _deliver(out, new_w)


def ftml_update(weight, grad, d, v, z, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                out=None, **kw):
    """ref: optimizer_op-inl.h:1214 FTMLKernel."""
    w, g = _d(weight), _d(grad)
    sd, sv, sz = _d(d), _d(v), _d(z)
    g = _clip(rescale_grad * g + wd * w, clip_grad)
    t = float(t)
    new_v = beta2 * sv + (1.0 - beta2) * g * g
    d_t = (1.0 - beta1 ** t) / lr * (
        jnp.sqrt(new_v / (1.0 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * sd
    new_z = beta1 * sz + (1.0 - beta1) * g - sigma * w
    d._data = d_t.astype(sd.dtype)
    v._data = new_v.astype(sv.dtype)
    z._data = new_z.astype(sz.dtype)
    return _deliver(out, -new_z / d_t)


def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op-inl.h:1998 SignSGDKernel."""
    w, g = _d(weight), _d(grad)
    return _deliver(out, (1.0 - lr * wd) * w - lr * jnp.sign(g))


def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0,
                  out=None, **kw):
    """ref: optimizer_op-inl.h:2066 SignumKernel."""
    w, g, m = _d(weight), _d(grad), _d(mom)
    g = _clip(rescale_grad * g, clip_gradient)
    new_m = momentum * m - (1.0 - momentum) * wd * w - (1.0 - momentum) * g
    mom._data = new_m.astype(m.dtype)
    return _deliver(out, (1.0 - lr * wd_lh) * w + lr * jnp.sign(new_m))


def adamw_update(weight, grad, mean, var, rescale_grad, lr, eta,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                 clip_gradient=-1.0, out=None, **kw):
    """Decoupled weight decay Adam (ref: src/operator/contrib/adamw.cc
    _adamw_update; rescale_grad is a TENSOR input there)."""
    w, g = _d(weight), _d(grad)
    m, v = _d(mean), _d(var)
    g = _clip(g * _scalar(rescale_grad), clip_gradient)
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * g * g
    mean._data = new_m.astype(m.dtype)
    var._data = new_v.astype(v.dtype)
    new_w = w - eta * (lr * new_m / (jnp.sqrt(new_v) + epsilon) + wd * w)
    return _deliver(out, new_w)


def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad, lr,
                    eta, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    clip_gradient=-1.0, out=None, **kw):
    """ref: src/operator/contrib/adamw.cc _mp_adamw_update."""
    w32 = _d(weight32)
    g = _d(grad).astype(jnp.float32)
    m, v = _d(mean), _d(var)
    g = _clip(g * _scalar(rescale_grad), clip_gradient)
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * g * g
    mean._data = new_m
    var._data = new_v
    new_w32 = w32 - eta * (lr * new_m / (jnp.sqrt(new_v) + epsilon)
                           + wd * w32)
    weight32._data = new_w32
    return _deliver(out if out is not None else weight,
                    new_w32.astype(_d(weight).dtype))


def lamb_update_phase1(weight, grad, mean, var, lr=None, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, t=1, bias_correction=True,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       out=None, **kw):
    """ref: src/operator/optimizer_op.cc lamb_update_phase1."""
    w, g = _d(weight), _d(grad)
    m, v = _d(mean), _d(var)
    g = _clip(rescale_grad * g, clip_gradient)
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * g * g
    mean._data = new_m.astype(m.dtype)
    var._data = new_v.astype(v.dtype)
    mh, vh = new_m, new_v
    if bias_correction:
        t = float(t)
        mh = new_m / (1.0 - beta1 ** t)
        vh = new_v / (1.0 - beta2 ** t)
    return _deliver(out, mh / (jnp.sqrt(vh) + epsilon) + wd * w)


def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None, **kw):
    """ref: src/operator/optimizer_op.cc lamb_update_phase2."""
    w, gd = _d(weight), _d(g)
    r1v, r2v = _d(r1), _d(r2)
    if lower_bound is not None and lower_bound >= 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1v > 0, r2v > 0), r1v / r2v, 1.0)
    return _deliver(out, w - lr * ratio * gd)


def sparse_adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0, out=None,
                          **kw):
    """AdaGrad with history state (ref: src/operator/optimizer_op.cc
    _sparse_adagrad_update; dense emulation of the row-sparse path)."""
    w, g, h = _d(weight), _d(grad), _d(history)
    g = _clip(rescale_grad * g, clip_gradient)
    new_h = h + g * g
    history._data = new_h.astype(h.dtype)
    return _deliver(out, w - lr * (g / (jnp.sqrt(new_h) + epsilon)
                                   + wd * w))


group_adagrad_update = sparse_adagrad_update  # ref: contrib/optimizer_op.cc


def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0, out=None, **kw):
    """LARS trust-ratio learning rates (ref: src/operator/contrib/
    multi_lars.cc)."""
    lr_v = _d(lrs)
    w2, g2, wd_v = _d(weights_sum_sq), _d(grads_sum_sq), _d(wds)
    wn = jnp.sqrt(w2)
    gn = jnp.sqrt(g2) * rescale_grad
    ratio = jnp.where(
        jnp.logical_and(wn > 0, gn > 0),
        eta * wn / (gn + wd_v * wn + eps), jnp.ones_like(wn))
    return _deliver(out, lr_v * ratio)


# -- multi-tensor variants ---------------------------------------------------

def _multi(update_fn, n_per, data, kwargs, num_weights, lrs, wds,
           state_slots):
    outs = []
    lrs = [float(x) for x in (lrs if isinstance(lrs, (tuple, list))
                              else [lrs] * num_weights)]
    wds = [float(x) for x in (wds if isinstance(wds, (tuple, list))
                              else [wds] * num_weights)]
    for i in range(num_weights):
        group = data[i * n_per:(i + 1) * n_per]
        outs.append(update_fn(*group, lr=lrs[i], wd=wds[i], **kwargs))
    return tuple(outs)


def multi_sgd_update(*data, lrs=None, wds=None, num_weights=1,
                     rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    """ref: src/operator/optimizer_op.cc multi_sgd_update — interleaved
    (weight, grad) x num_weights."""
    res = _multi(sgd_update, 2, data,
                 dict(rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 0)
    return _deliver_multi(out, res)


def multi_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1,
                         momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc multi_sgd_mom_update — (w, g, mom) x N."""
    res = _multi(sgd_mom_update, 3, data,
                 dict(momentum=momentum, rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 1)
    return _deliver_multi(out, res)


def multi_mp_sgd_update(*data, lrs=None, wds=None, num_weights=1,
                        rescale_grad=1.0, clip_gradient=-1.0, out=None,
                        **kw):
    """ref: optimizer_op.cc multi_mp_sgd_update — (w, g, w32) x N."""
    res = _multi(mp_sgd_update, 3, data,
                 dict(rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 1)
    return _deliver_multi(out, res)


def multi_mp_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1,
                            momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc multi_mp_sgd_mom_update — (w, g, mom, w32)."""
    res = _multi(mp_sgd_mom_update, 4, data,
                 dict(momentum=momentum, rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 2)
    return _deliver_multi(out, res)


def _deliver_multi(out, res):
    if out is None:
        return res
    outs = out if isinstance(out, (tuple, list)) else [out]
    for o, r in zip(outs, res):
        if o is not None and o is not r:
            o._data = r._data
    return tuple(outs) if len(outs) > 1 else outs[0]


def _preloaded(update_multi, n_per, data, num_weights, kwargs, out):
    # trailing two tensor inputs are the preloaded lrs and wds vectors
    # (ref: optimizer_op.cc preloaded_multi_sgd_update)
    import numpy as _np
    lrs = _np.asarray(_d(data[-2])).tolist()
    wds = _np.asarray(_d(data[-1])).tolist()
    return update_multi(*data[:-2], lrs=lrs, wds=wds,
                        num_weights=num_weights, out=out, **kwargs)


def preloaded_multi_sgd_update(*data, num_weights=1, rescale_grad=1.0,
                               clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_sgd_update."""
    return _preloaded(multi_sgd_update, 2, data, int(num_weights),
                      dict(rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)


def preloaded_multi_sgd_mom_update(*data, num_weights=1, momentum=0.0,
                                   rescale_grad=1.0, clip_gradient=-1.0,
                                   out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_sgd_mom_update."""
    return _preloaded(multi_sgd_mom_update, 3, data, int(num_weights),
                      dict(momentum=momentum, rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)


def preloaded_multi_mp_sgd_update(*data, num_weights=1, rescale_grad=1.0,
                                  clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_mp_sgd_update."""
    return _preloaded(multi_mp_sgd_update, 3, data, int(num_weights),
                      dict(rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)


def preloaded_multi_mp_sgd_mom_update(*data, num_weights=1, momentum=0.0,
                                      rescale_grad=1.0, clip_gradient=-1.0,
                                      out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_mp_sgd_mom_update."""
    return _preloaded(multi_mp_sgd_mom_update, 4, data, int(num_weights),
                      dict(momentum=momentum, rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)
