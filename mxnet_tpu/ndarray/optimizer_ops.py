"""Fused optimizer update ops with the reference's in-place semantics.

ref: src/operator/optimizer_op.cc registrations (kernel line refs live
with the math in ops/optimizer_ops.py, the pure functional registry
layer these wrappers share with the symbolic executor).

These are the nd-level entry points (`mx.nd.sgd_update(w, g, out=w, ...)`)
that the reference's Python optimizers call into. State inputs (momentum,
mean/var, n/z/...) are updated IN PLACE on the passed NDArrays, and the
new weight is returned (written into ``out`` when given) — exactly the
reference's calling convention. The pure ops return every updated tensor
explicitly (XLA has no aliasing); this layer maps those outputs back onto
the state NDArrays.

On TPU each call XLA-dispatches a small fused program; for whole-step
fusion use ShardedTrainStep (parallel/train.py), which compiles the
forward+backward+update pipeline into one program instead.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import optimizer_ops as _pure  # noqa: F401 — registration side effect
from ..ops import registry as _registry
from . import register as _register
from .ndarray import NDArray


def _invoke(name, *tensors, **statics):
    """Run a registry optimizer op through the imperative dispatch choke
    point: the jitted cache (MXNET_IMPERATIVE_JIT) applies, and the op's
    OpDef.inplace marks donate the STATE buffers on accelerator backends
    (states are unconditionally rebound by _assign below — the relinquish
    donation requires; the weight is never donated because pure-form
    callers keep it readable). Returns NDArray(s), possibly still pending
    inside an engine.bulk segment."""
    return _register.invoke(
        _registry.get_op(name),
        tuple(t if isinstance(t, NDArray) else NDArray(jnp.asarray(t))
              for t in tensors), statics)


# dst <- src delivery preserving dst dtype; adopts still-pending bulk
# results (one shared implementation with the out= delivery path)
_assign = _register.deliver_result

__all__ = [
    "sgd_update", "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update",
    "nag_mom_update", "mp_nag_mom_update", "adam_update", "rmsprop_update",
    "rmspropalex_update", "ftrl_update", "ftml_update", "signsgd_update",
    "signum_update", "adamw_update", "mp_adamw_update",
    "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
    "multi_mp_sgd_mom_update", "preloaded_multi_sgd_update",
    "preloaded_multi_sgd_mom_update", "preloaded_multi_mp_sgd_update",
    "preloaded_multi_mp_sgd_mom_update", "multi_lars",
    "sparse_adagrad_update", "group_adagrad_update", "lamb_update_phase1",
    "lamb_update_phase2",
]


def _d(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _scalar(v):
    """Scalar attrs pass through as floats; NDArray-valued ones (adamw's
    tensor rescale_grad) stay NDArrays so _invoke treats them as tensor
    inputs."""
    return float(v) if not isinstance(v, NDArray) else v


def _deliver(out, new_w):
    if out is not None:
        return _assign(out, new_w)
    return new_w


def _writeback(states, new_vals):
    """Map the pure op's extra outputs onto the state NDArrays in place,
    preserving each state's dtype (the reference mutates them)."""
    for st, new in zip(states, new_vals):
        _assign(st, new)


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, out=None, **kw):
    new_w = _invoke("sgd_update", weight, grad, lr=lr, wd=wd,
                             rescale_grad=rescale_grad,
                             clip_gradient=clip_gradient)
    return _deliver(out, new_w)


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   out=None, **kw):
    new_w, new_m = _invoke("sgd_mom_update", 
        weight, grad, mom, lr=lr, momentum=momentum, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    _writeback([mom], [new_m])
    return _deliver(out, new_w)


def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True, out=None, **kw):
    new_w, new_w32 = _invoke("mp_sgd_update", 
        weight, grad, weight32, lr=lr, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    _assign(weight32, new_w32)
    return _deliver(out if out is not None else weight, new_w)


def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True, out=None, **kw):
    new_w, new_m, new_w32 = _invoke("mp_sgd_mom_update", 
        weight, grad, mom, weight32, lr=lr,
        momentum=momentum, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient)
    _assign(mom, new_m)
    _assign(weight32, new_w32)
    return _deliver(out if out is not None else weight, new_w)


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    new_w, new_m = _invoke("nag_mom_update", 
        weight, grad, mom, lr=lr, momentum=momentum, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    _writeback([mom], [new_m])
    return _deliver(out, new_w)


def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      out=None, **kw):
    new_w, new_m, new_w32 = _invoke("mp_nag_mom_update", 
        weight, grad, mom, weight32, lr=lr,
        momentum=momentum, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient)
    _assign(mom, new_m)
    _assign(weight32, new_w32)
    return _deliver(out if out is not None else weight, new_w)


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, out=None, **kw):
    new_w, new_m, new_v = _invoke("adam_update", 
        weight, grad, mean, var, lr=lr, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient)
    _writeback([mean, var], [new_m, new_v])
    return _deliver(out, new_w)


def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0,
                   out=None, **kw):
    new_w, new_n = _invoke("rmsprop_update", 
        weight, grad, n, lr=lr, gamma1=gamma1, epsilon=epsilon,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient,
        clip_weights=clip_weights)
    _writeback([n], [new_n])
    return _deliver(out, new_w)


def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, out=None,
                       **kw):
    new_w, new_n, new_g, new_d = _invoke("rmspropalex_update", 
        weight, grad, n, g, delta, lr=lr,
        gamma1=gamma1, gamma2=gamma2, epsilon=epsilon, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient,
        clip_weights=clip_weights)
    _writeback([n, g, delta], [new_n, new_g, new_d])
    return _deliver(out, new_w)


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    new_w, new_z, new_n = _invoke("ftrl_update", 
        weight, grad, z, n, lr=lr, lamda1=lamda1,
        beta=beta, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient)
    _writeback([z, n], [new_z, new_n])
    return _deliver(out, new_w)


def ftml_update(weight, grad, d, v, z, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                out=None, **kw):
    new_w, new_d, new_v, new_z = _invoke("ftml_update", 
        weight, grad, d, v, z, lr=lr, t=t,
        beta1=beta1, beta2=beta2, epsilon=epsilon, wd=wd,
        rescale_grad=rescale_grad, clip_grad=clip_grad)
    _writeback([d, v, z], [new_d, new_v, new_z])
    return _deliver(out, new_w)


def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None, **kw):
    new_w = _invoke("signsgd_update", weight, grad, lr=lr, wd=wd,
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
    return _deliver(out, new_w)


def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0,
                  out=None, **kw):
    new_w, new_m = _invoke("signum_update", 
        weight, grad, mom, lr=lr, momentum=momentum, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient,
        wd_lh=wd_lh)
    _writeback([mom], [new_m])
    return _deliver(out, new_w)


def adamw_update(weight, grad, mean, var, rescale_grad, lr, eta,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                 clip_gradient=-1.0, out=None, **kw):
    """rescale_grad is a TENSOR input in the reference (adamw.cc); both
    scalar and NDArray are accepted here."""
    new_w, new_m, new_v = _invoke("adamw_update", 
        weight, grad, mean, var,
        rescale_grad=_scalar(rescale_grad), lr=lr, eta=eta, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, clip_gradient=clip_gradient)
    _writeback([mean, var], [new_m, new_v])
    return _deliver(out, new_w)


def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad, lr,
                    eta, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    clip_gradient=-1.0, out=None, **kw):
    new_w, new_m, new_v, new_w32 = _invoke("mp_adamw_update", 
        weight, grad, mean, var, weight32,
        rescale_grad=_scalar(rescale_grad), lr=lr, eta=eta, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, clip_gradient=clip_gradient)
    _assign(mean, new_m)
    _assign(var, new_v)
    _assign(weight32, new_w32)
    return _deliver(out if out is not None else weight, new_w)


def lamb_update_phase1(weight, grad, mean, var, lr=None, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, t=1, bias_correction=True,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       out=None, **kw):
    g_out, new_m, new_v = _invoke("lamb_update_phase1", 
        weight, grad, mean, var, lr=lr, beta1=beta1,
        beta2=beta2, epsilon=epsilon, t=t, bias_correction=bias_correction,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    _writeback([mean, var], [new_m, new_v])
    return _deliver(out, g_out)


def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None, **kw):
    new_w = _invoke("lamb_update_phase2", 
        weight, g, r1, r2, lr=lr,
        lower_bound=lower_bound, upper_bound=upper_bound)
    return _deliver(out, new_w)


def sparse_adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0, out=None,
                          **kw):
    """Dense emulation of the row-sparse path (ref: optimizer_op.cc
    _sparse_adagrad_update)."""
    new_w, new_h = _invoke("sparse_adagrad_update", 
        weight, grad, history, lr=lr, epsilon=epsilon, wd=wd,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    _writeback([history], [new_h])
    return _deliver(out, new_w)


group_adagrad_update = sparse_adagrad_update  # ref: contrib/optimizer_op.cc


def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0, out=None, **kw):
    new_lrs = _invoke("multi_lars", lrs, weights_sum_sq,
                               grads_sum_sq, wds, eta=eta, eps=eps,
                               rescale_grad=rescale_grad)
    return _deliver(out, new_lrs)


# -- multi-tensor variants ---------------------------------------------------

def _multi(update_fn, n_per, data, kwargs, num_weights, lrs, wds,
           state_slots):
    outs = []
    lrs = [float(x) for x in (lrs if isinstance(lrs, (tuple, list))
                              else [lrs] * num_weights)]
    wds = [float(x) for x in (wds if isinstance(wds, (tuple, list))
                              else [wds] * num_weights)]
    for i in range(num_weights):
        group = data[i * n_per:(i + 1) * n_per]
        outs.append(update_fn(*group, lr=lrs[i], wd=wds[i], **kwargs))
    return tuple(outs)


def multi_sgd_update(*data, lrs=None, wds=None, num_weights=1,
                     rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    """ref: src/operator/optimizer_op.cc multi_sgd_update — interleaved
    (weight, grad) x num_weights."""
    res = _multi(sgd_update, 2, data,
                 dict(rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 0)
    return _deliver_multi(out, res)


def multi_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1,
                         momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc multi_sgd_mom_update — (w, g, mom) x N."""
    res = _multi(sgd_mom_update, 3, data,
                 dict(momentum=momentum, rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 1)
    return _deliver_multi(out, res)


def multi_mp_sgd_update(*data, lrs=None, wds=None, num_weights=1,
                        rescale_grad=1.0, clip_gradient=-1.0, out=None,
                        **kw):
    """ref: optimizer_op.cc multi_mp_sgd_update — (w, g, w32) x N."""
    res = _multi(mp_sgd_update, 3, data,
                 dict(rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 1)
    return _deliver_multi(out, res)


def multi_mp_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1,
                            momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc multi_mp_sgd_mom_update — (w, g, mom, w32)."""
    res = _multi(mp_sgd_mom_update, 4, data,
                 dict(momentum=momentum, rescale_grad=rescale_grad,
                      clip_gradient=clip_gradient),
                 int(num_weights), lrs, wds, 2)
    return _deliver_multi(out, res)


def _deliver_multi(out, res):
    if out is None:
        return res
    outs = out if isinstance(out, (tuple, list)) else [out]
    for o, r in zip(outs, res):
        if o is not None and o is not r:
            o._data = r._data
    return tuple(outs) if len(outs) > 1 else outs[0]


def _preloaded(update_multi, n_per, data, num_weights, kwargs, out):
    # trailing two tensor inputs are the preloaded lrs and wds vectors
    # (ref: optimizer_op.cc preloaded_multi_sgd_update)
    import numpy as _np
    lrs = _np.asarray(_d(data[-2])).tolist()
    wds = _np.asarray(_d(data[-1])).tolist()
    return update_multi(*data[:-2], lrs=lrs, wds=wds,
                        num_weights=num_weights, out=out, **kwargs)


def preloaded_multi_sgd_update(*data, num_weights=1, rescale_grad=1.0,
                               clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_sgd_update."""
    return _preloaded(multi_sgd_update, 2, data, int(num_weights),
                      dict(rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)


def preloaded_multi_sgd_mom_update(*data, num_weights=1, momentum=0.0,
                                   rescale_grad=1.0, clip_gradient=-1.0,
                                   out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_sgd_mom_update."""
    return _preloaded(multi_sgd_mom_update, 3, data, int(num_weights),
                      dict(momentum=momentum, rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)


def preloaded_multi_mp_sgd_update(*data, num_weights=1, rescale_grad=1.0,
                                  clip_gradient=-1.0, out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_mp_sgd_update."""
    return _preloaded(multi_mp_sgd_update, 3, data, int(num_weights),
                      dict(rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)


def preloaded_multi_mp_sgd_mom_update(*data, num_weights=1, momentum=0.0,
                                      rescale_grad=1.0, clip_gradient=-1.0,
                                      out=None, **kw):
    """ref: optimizer_op.cc preloaded_multi_mp_sgd_mom_update."""
    return _preloaded(multi_mp_sgd_mom_update, 4, data, int(num_weights),
                      dict(momentum=momentum, rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient), out)
