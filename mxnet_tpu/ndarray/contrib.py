"""mx.nd.contrib — control flow + misc contrib ops.

ref: python/mxnet/ndarray/contrib.py (foreach :216, while_loop :331,
cond :460) over src/operator/control_flow.cc:1089/1150/1211. The
reference's imperative versions run Python loops per step; these do the
same eagerly (each step's ops XLA-dispatch). ``foreach`` also traces
cleanly into an enclosing ``hybridize``/jit (its trip count is static);
``while_loop``/``cond`` inspect predicate VALUES on the host, so they are
eager-only — inside jit use ``jax.lax.while_loop``/``lax.cond`` (or
``F.where`` masks) directly. For O(1)-size traced loops over long
sequences use the fused ops (e.g. ``nd.RNN``) or ``jax.lax.scan``.
"""
from __future__ import annotations

from . import NDArray
from . import stack as _stack

__all__ = ["foreach", "while_loop", "cond", "boolean_mask",
           "arange_like", "quantize", "dequantize"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def foreach(body, data, init_states):
    """Iterate ``body(data_t, states) -> (out, new_states)`` over axis 0 of
    ``data``; outputs are stacked (ref: ndarray/contrib.py:216 foreach)."""
    single_data = isinstance(data, NDArray)
    seqs = [data] if single_data else list(data)
    if not seqs:
        raise ValueError("foreach requires at least one input sequence")
    length = seqs[0].shape[0]
    for s in seqs[1:]:
        if s.shape[0] != length:
            # jax indexing would silently clamp out-of-bounds steps
            raise ValueError(
                "foreach input sequences must share axis-0 length; got "
                "%d and %d" % (length, s.shape[0]))
    states = init_states
    outs = []
    for t in range(length):
        slices = [s[t] for s in seqs]
        out, states = body(slices[0] if single_data else slices, states)
        outs.append(out)
    if not outs:
        raise ValueError("foreach over empty data")
    if isinstance(outs[0], (list, tuple)):
        stacked = [_stack(*[o[i] for o in outs], axis=0)
                   for i in range(len(outs[0]))]
    else:
        stacked = _stack(*outs, axis=0)
    return stacked, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """``while cond(*loop_vars): step_out, loop_vars = func(*loop_vars)``
    with outputs stacked and padded to ``max_iterations``
    (ref: ndarray/contrib.py:331 while_loop)."""
    if max_iterations is None:
        raise ValueError("max_iterations must be provided")
    loop_vars = _as_list(loop_vars)
    outs = []
    steps = 0

    def _pred(v):
        import numpy as _onp
        return bool(_onp.asarray(v.asnumpy()).item())

    while steps < max_iterations and _pred(cond(*loop_vars)):
        step_out, new_vars = func(*loop_vars)
        outs.append(_as_list(step_out))
        loop_vars = _as_list(new_vars)
        steps += 1
    if not outs:
        # output shapes are unknowable without one func step; the
        # reference's imperative while_loop rejects this case too
        raise ValueError("while_loop ran zero steps (cond was false at "
                         "entry); outputs would have unknown shape")
    from . import zeros as _zeros
    n_out = len(outs[0])
    stacked = []
    for i in range(n_out):
        col = _stack(*[o[i] for o in outs], axis=0)
        if steps < max_iterations:
            # pad to max_iterations like the reference's static output
            pad = _zeros((max_iterations - steps,) + col.shape[1:],
                         dtype=str(col.dtype))
            from . import concat as _concat
            col = _concat(col, pad, dim=0)
        stacked.append(col)
    return stacked, loop_vars


def cond(pred, then_func, else_func):
    """Run one branch based on a scalar predicate
    (ref: ndarray/contrib.py:460 cond)."""
    import numpy as _onp
    p = bool(_onp.asarray(pred.asnumpy()).item()) \
        if isinstance(pred, NDArray) else bool(pred)
    return then_func() if p else else_func()


# convenience re-exports under the reference's contrib namespace
from . import boolean_mask  # noqa: E402,F401
from ..numpy_extension import arange_like  # noqa: E402,F401
from ..contrib.quantization import quantize, dequantize  # noqa: E402,F401

# DGL graph-sampling family (eager host-side CSR ops — ref:
# src/operator/contrib/dgl_graph.cc, CPU-only FComputeEx there too)
from .graph import (dgl_csr_neighbor_uniform_sample,       # noqa: E402,F401
                    dgl_csr_neighbor_non_uniform_sample,   # noqa: E402,F401
                    dgl_subgraph, edge_id, dgl_adjacency,  # noqa: E402,F401
                    dgl_graph_compact, getnnz)             # noqa: E402,F401


def _populate_contrib():
    """Expose every registered ``_contrib_X`` op as ``nd.contrib.X`` (the
    reference generates these into the contrib module the same way —
    ref: python/mxnet/ndarray/register.py _init_op_module('contrib'))."""
    from ..ops import registry as _registry
    from .register import make_op_func
    g = globals()
    for name in _registry.list_ops():
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            if short not in g:
                g[short] = make_op_func(_registry.get_op(name), short)


_populate_contrib()
