"""DGL graph-sampling operator family — eager host-side implementations.

ref: src/operator/contrib/dgl_graph.cc — `_contrib_dgl_csr_neighbor_
{uniform,non_uniform}_sample` (:744/:838), `_contrib_dgl_subgraph`
(:1115), `_contrib_edge_id` (:1300), `_contrib_dgl_adjacency` (:1376),
`_contrib_dgl_graph_compact` (:1551), plus `_contrib_getnnz`
(src/operator/contrib/nnz.cc).

Design note: the reference implements these CPU-only (FComputeEx<cpu>)
because graph sampling is inherently dynamic-shape, data-dependent
work — the same reasoning holds on TPU, where XLA requires static
shapes. These run eagerly on host numpy against CSRNDArray storage
(the host-callback tier of the op surface), exactly the role the
reference's CPU kernels play next to its GPU ops. Outputs are padded
to `max_num_vertices` like the reference so downstream device code
sees static shapes.
"""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray
from .sparse import CSRNDArray, csr_matrix

__all__ = ["dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
           "edge_id", "dgl_adjacency", "dgl_graph_compact", "getnnz"]


def _csr_parts(a):
    if isinstance(a, CSRNDArray):
        return (np.asarray(a.indptr.asnumpy(), np.int64),
                np.asarray(a.indices.asnumpy(), np.int64),
                np.asarray(a.data.asnumpy()), a.shape)
    dense = np.asarray(a.asnumpy() if isinstance(a, NDArray) else a)
    indptr = [0]
    indices = []
    data = []
    for row in dense:
        nz = np.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return (np.asarray(indptr, np.int64), np.asarray(indices, np.int64),
            np.asarray(data), dense.shape)


def _rng():
    from .. import random as _random
    return np.random.RandomState(
        int(np.asarray(_random.next_key())[-1]) % (2 ** 31))


def _neighbor_sample(csr, seeds, num_hops, num_neighbor, max_num_vertices,
                     prob=None):
    indptr, indices, data, shape = _csr_parts(csr)
    max_v = int(max_num_vertices)
    found = {}          # vertex -> hop layer
    frontier = []
    seed_list = [int(v) for v in np.asarray(seeds.asnumpy()).ravel()
                 if v >= 0]
    if len(set(seed_list)) > max_v:
        raise ValueError(
            "neighbor_sample: %d distinct seeds exceed max_num_vertices=%d"
            % (len(set(seed_list)), max_v))
    for s in seed_list:
        if s not in found:
            found[s] = 0
            frontier.append(s)
    edges = {}          # (u, v) -> value
    rng = _rng()
    for hop in range(1, int(num_hops) + 1):
        nxt = []
        for u in frontier:
            row = indices[indptr[u]:indptr[u + 1]]
            vals = data[indptr[u]:indptr[u + 1]]
            if len(row) == 0:
                continue
            k = min(int(num_neighbor), len(row))
            if prob is not None:
                p = np.asarray(prob.asnumpy()).ravel()[row]
                psum = p.sum()
                if psum <= 0:
                    continue
                # replace=False cannot draw more than the nonzero support
                k = min(k, int(np.count_nonzero(p)))
                sel = rng.choice(len(row), size=k, replace=False,
                                 p=p / psum)
            else:
                sel = rng.choice(len(row), size=k, replace=False)
            for si in sel:
                v = int(row[si])
                if len(found) >= max_v and v not in found:
                    continue
                edges[(u, v)] = vals[si]
                if v not in found:
                    found[v] = hop
                    nxt.append(v)
        frontier = nxt
    verts = sorted(found)
    n = len(verts)
    out_v = np.full((max_v + 1,), -1, np.int64)
    out_v[:n] = verts
    out_v[-1] = n
    layer = np.full((max_v,), -1, np.int64)
    layer[:n] = [found[v] for v in verts]
    # build the sampled-edge CSR directly (no dense (V, V) intermediate —
    # these ops exist for graphs where that would be O(V^2))
    vdt = data.dtype if data.size else np.int64
    by_row = {}
    for (u, v), val in edges.items():
        by_row.setdefault(u, []).append((v, val))
    s_indptr = np.zeros((shape[0] + 1,), np.int64)
    s_indices = []
    s_data = []
    for r in range(shape[0]):
        for c, val in sorted(by_row.get(r, ())):
            s_indices.append(c)
            s_data.append(val)
        s_indptr[r + 1] = len(s_indices)
    sub = csr_matrix((np.asarray(s_data, vdt),
                      np.asarray(s_indices, np.int64), s_indptr),
                     shape=shape)
    return (NDArray(np.asarray(out_v)), sub, NDArray(np.asarray(layer)))


def dgl_csr_neighbor_uniform_sample(csr, *seeds, num_args=2, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100):
    """BFS neighbor sampling with uniform probability
    (ref: dgl_graph.cc:744). Returns, per seed array: (vertices
    [max_num_vertices+1, last = count], sampled-edge CSR, layer array)."""
    outs = []
    for s in seeds:
        outs.extend(_neighbor_sample(csr, s, num_hops, num_neighbor,
                                     max_num_vertices))
    return tuple(outs)


def dgl_csr_neighbor_non_uniform_sample(csr, prob, *seeds, num_args=3,
                                        num_hops=1, num_neighbor=2,
                                        max_num_vertices=100):
    """Weighted neighbor sampling (ref: dgl_graph.cc:838)."""
    outs = []
    for s in seeds:
        outs.extend(_neighbor_sample(csr, s, num_hops, num_neighbor,
                                     max_num_vertices, prob=prob))
    return tuple(outs)


def dgl_subgraph(graph, *vids, num_args=2, return_mapping=False):
    """Induced subgraph on vertex set(s) (ref: dgl_graph.cc:1115).
    With return_mapping, also returns the CSR holding original edge
    ids."""
    indptr, indices, data, shape = _csr_parts(graph)
    outs = []
    for v in vids:
        vl = [int(x) for x in np.asarray(v.asnumpy()).ravel()]
        vset = {x: i for i, x in enumerate(vl)}
        n = len(vl)
        new = np.zeros((n, n), np.int64)
        orig = np.zeros((n, n), data.dtype if data.size else np.int64)
        eid = 1
        for i, u in enumerate(vl):
            row = indices[indptr[u]:indptr[u + 1]]
            vals = data[indptr[u]:indptr[u + 1]]
            for c, val in zip(row, vals):
                j = vset.get(int(c))
                if j is not None:
                    new[i, j] = eid
                    orig[i, j] = val
                    eid += 1
        outs.append(csr_matrix(new))
        if return_mapping:
            outs.append(csr_matrix(orig))
    return tuple(outs) if len(outs) > 1 else outs[0]


def edge_id(data, u, v):
    """out[i] = data[u[i], v[i]] if the edge exists else -1
    (ref: dgl_graph.cc:1300)."""
    indptr, indices, vals, shape = _csr_parts(data)
    uu = np.asarray(u.asnumpy(), np.int64).ravel()
    vv = np.asarray(v.asnumpy(), np.int64).ravel()
    out = np.full((len(uu),), -1.0, np.float32)
    for i, (a, b) in enumerate(zip(uu, vv)):
        row = indices[indptr[a]:indptr[a + 1]]
        hit = np.nonzero(row == b)[0]
        if hit.size:
            out[i] = vals[indptr[a] + hit[0]]
    return NDArray(out)


def dgl_adjacency(data):
    """CSR edge-id matrix -> float32 adjacency with the same structure
    (ref: dgl_graph.cc:1376). Reuses indptr/indices; only values change."""
    indptr, indices, vals, shape = _csr_parts(data)
    return csr_matrix((np.ones((len(indices),), np.float32), indices,
                       indptr), shape=shape)


def dgl_graph_compact(*graph_data, num_args=2, return_mapping=False,
                      graph_sizes=()):
    """Remove the padding rows/cols of sampled sub-CSRs by renumbering
    through the vertex arrays (ref: dgl_graph.cc:1551). Inputs are the
    sampled CSR(s) followed by their vertex array(s)."""
    k = len(graph_data) // 2
    csrs, vids = graph_data[:k], graph_data[k:]
    sizes = ([int(graph_sizes)] * k if np.isscalar(graph_sizes)
             else [int(s) for s in graph_sizes])
    outs = []
    for g, v, n in zip(csrs, vids, sizes):
        indptr, indices, vals, shape = _csr_parts(g)
        vl = [int(x) for x in np.asarray(v.asnumpy()).ravel()[:n]]
        vmap = {x: i for i, x in enumerate(vl)}
        # same convention as dgl_subgraph: first output renumbers edges
        # 1..E, the mapping output keeps the original edge values
        new = np.zeros((n, n), np.int64)
        orig = np.zeros((n, n), vals.dtype if vals.size else np.int64)
        eid = 1
        for u in vl:
            row = indices[indptr[u]:indptr[u + 1]]
            rv = vals[indptr[u]:indptr[u + 1]]
            for c, val in zip(row, rv):
                j = vmap.get(int(c))
                if j is not None:
                    new[vmap[u], j] = eid
                    orig[vmap[u], j] = val
                    eid += 1
        # ref example (dgl_graph.cc:1551) shows sequentially renumbered
        # edge ids in the primary output; the mapping carries originals
        outs.append(csr_matrix(new))
        if return_mapping:
            outs.append(csr_matrix(orig))
    return tuple(outs) if len(outs) > 1 else outs[0]


def getnnz(data, axis=None):
    """Stored-value count of a CSR (ref: src/operator/contrib/nnz.cc).
    axis=None -> scalar; axis=1 -> per-row counts."""
    indptr, indices, vals, shape = _csr_parts(data)
    if axis is None:
        return NDArray(np.asarray(len(indices), np.int64))
    if int(axis) == 1:
        return NDArray(np.diff(indptr).astype(np.int64))
    raise ValueError("getnnz: axis must be None or 1 (ref nnz.cc)")
