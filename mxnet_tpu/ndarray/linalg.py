"""``mx.nd.linalg`` namespace (ref: python/mxnet/ndarray/linalg.py)."""
from __future__ import annotations

from .register import invoke_by_name as _inv

__all__ = ["gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "sumlogdiag",
           "extractdiag", "makediag", "syrk", "gelqf", "inverse", "det",
           "slogdet"]


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
         axis=-2, **kw):
    return _inv("linalg_gemm", A, B, C, transpose_a=transpose_a,
                transpose_b=transpose_b, alpha=alpha, beta=beta, axis=axis)


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2, **kw):
    return _inv("linalg_gemm2", A, B, transpose_a=transpose_a,
                transpose_b=transpose_b, alpha=alpha, axis=axis)


def potrf(A, lower=True, **kw):
    return _inv("linalg_potrf", A, lower=lower)


def potri(A, lower=True, **kw):
    return _inv("linalg_potri", A, lower=lower)


def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **kw):
    return _inv("linalg_trmm", A, B, transpose=transpose, rightside=rightside,
                lower=lower, alpha=alpha)


def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **kw):
    return _inv("linalg_trsm", A, B, transpose=transpose, rightside=rightside,
                lower=lower, alpha=alpha)


def sumlogdiag(A, **kw):
    return _inv("linalg_sumlogdiag", A)


def extractdiag(A, offset=0, **kw):
    return _inv("linalg_extractdiag", A, offset=offset)


def makediag(d, offset=0, **kw):
    return _inv("linalg_makediag", d, offset=offset)


def syrk(A, transpose=False, alpha=1.0, **kw):
    return _inv("linalg_syrk", A, transpose=transpose, alpha=alpha)


def gelqf(A, **kw):
    return _inv("linalg_gelqf", A)


def inverse(A, **kw):
    return _inv("linalg_inverse", A)


def det(A, **kw):
    return _inv("linalg_det", A)


def slogdet(A, **kw):
    return _inv("linalg_slogdet", A)
