"""Sparse NDArray: row_sparse and CSR.

TPU-native take on the reference's sparse storage types
(ref: include/mxnet/ndarray.h:63-82 kRowSparseStorage/kCSRStorage,
python/mxnet/ndarray/sparse.py). XLA has no native sparse tensors; the
design keeps the *API and storage format* (indices+values / indptr+indices+
data), with index/value extraction running ON DEVICE (eager jnp.nonzero /
gather — no host round-trip), while heavy compute densifies. Row-sparse
is the communication format: kvstore push/pull of embedding grads ships
only touched rows (ref: src/kvstore/kvstore_dist.h:522), with wire-byte
accounting to prove it (kvstore.bytes_pushed).
"""
from __future__ import annotations

# mxlint: disable-file=MX001 (whole-file design exemption, see docstring:
# sparse storage-format extraction runs as eager device compute on the
# RAW buffers — indices/indptr manipulation is not an op-registry path,
# and routing it through invoke would put storage bookkeeping on the
# autograd tape and in the dispatch cache)
import jax.numpy as jnp
import numpy as _np

from .ndarray import NDArray, array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "zeros"]


class RowSparseNDArray(NDArray):
    """Row-sparse: (indices[k], values[k, ...]) with dense shape (n, ...)."""

    __slots__ = ("_indices", "_values")

    def __init__(self, data, indices=None, values=None, ctx=None):
        super().__init__(data, ctx=ctx)
        self._indices = indices
        self._values = values

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        if self._indices is None:
            # on-device nonzero (eager jax supports the dynamic result
            # shape); replaces the old asnumpy()+np.nonzero host sync
            row_norm = jnp.abs(self._data).reshape(
                self.shape[0], -1).sum(axis=1)
            nz = jnp.nonzero(row_norm)[0]
            self._indices = NDArray(nz.astype(jnp.int32))
        return self._indices

    @property
    def data(self):
        if self._values is None:
            # device gather of the touched rows
            self._values = NDArray(
                jnp.take(self._data, self.indices._data, axis=0))
        return self._values

    @property
    def wire_nbytes(self):
        """Bytes this array costs on the wire in sparse form
        (values + indices) — what kvstore push/pull accounts
        (ref: kvstore_dist.h:522 row-sparse key encoding)."""
        return int(self.data.nbytes) + int(self.indices.nbytes)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self._ctx)
        return cast_storage(self, stype)

    def retain(self, indices):
        """Keep only given rows (ref: sparse retain op) — device-side
        scatter mask, no host round-trip."""
        idx = indices._data if isinstance(indices, NDArray) \
            else jnp.asarray(_np.asarray(indices, _np.int64))
        mask = jnp.zeros((self.shape[0],), bool).at[
            idx.astype(jnp.int32)].set(True)
        dense = self._data * mask.reshape(
            (-1,) + (1,) * (self.ndim - 1)).astype(self._data.dtype)
        return RowSparseNDArray(dense, ctx=self._ctx)


class CSRNDArray(NDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_indptr", "_indices", "_values")

    def __init__(self, data, indptr=None, indices=None, values=None, ctx=None):
        super().__init__(data, ctx=ctx)
        self._indptr = indptr
        self._indices = indices
        self._values = values

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self):
        self._materialize()
        return self._indptr

    @property
    def indices(self):
        self._materialize()
        return self._indices

    @property
    def data(self):
        self._materialize()
        return self._values

    def _materialize(self):
        if self._indptr is None:
            dense = self.asnumpy()
            # vectorized extraction (a per-row Python loop would cost
            # minutes on realistically sized matrices)
            rows, cols = _np.nonzero(dense)
            counts = _np.bincount(rows, minlength=dense.shape[0])
            indptr = _np.concatenate([[0], _np.cumsum(counts)])
            self._indptr = array(indptr.astype(_np.int64))
            self._indices = array(cols.astype(_np.int64))
            self._values = array(dense[rows, cols])

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self._ctx)
        return cast_storage(self, stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Build from (values, indices) or a dense array-like.
    ref: python/mxnet/ndarray/sparse.py row_sparse_array."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        values = values._data if isinstance(values, NDArray) \
            else jnp.asarray(_np.asarray(
                values, _np.float32 if dtype is None else dtype))
        indices_dev = indices._data if isinstance(indices, NDArray) \
            else jnp.asarray(_np.asarray(indices, _np.int64))
        if shape is None:
            # dense shape is static metadata; deriving it from the index
            # values is the one place a host read is unavoidable
            n = int(indices_dev.max()) + 1 if indices_dev.size else 0
            full_shape = (n,) + tuple(values.shape[1:])
        else:
            full_shape = tuple(shape)
        # device scatter of the rows into the dense view
        dense = jnp.zeros(full_shape, values.dtype).at[
            indices_dev.astype(jnp.int32)].set(values)
        return RowSparseNDArray(dense, indices=NDArray(indices_dev),
                                values=NDArray(values), ctx=ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    return RowSparseNDArray(jnp.asarray(src), ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Build from (data, indices, indptr) or dense. ref: sparse.py csr_matrix."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = _np.asarray(data.asnumpy() if isinstance(data, NDArray) else data)
        indices = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                              else indices, _np.int64)
        indptr = _np.asarray(indptr.asnumpy() if isinstance(indptr, NDArray)
                             else indptr, _np.int64)
        nrow = len(indptr) - 1
        ncol = shape[1] if shape else (int(indices.max()) + 1 if len(indices) else 0)
        dense = _np.zeros((nrow, ncol), data.dtype)
        for r in range(nrow):
            cols = indices[indptr[r]:indptr[r + 1]]
            dense[r, cols] = data[indptr[r]:indptr[r + 1]]
        return CSRNDArray(jnp.asarray(dense), ctx=ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    return CSRNDArray(jnp.asarray(src), ctx=ctx)


def cast_storage(arr, stype):
    """ref: src/operator/tensor/cast_storage.cc."""
    if stype == "default":
        return NDArray(arr._data, ctx=arr._ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(arr._data, ctx=arr._ctx)
    if stype == "csr":
        return CSRNDArray(arr._data, ctx=arr._ctx)
    raise ValueError("unknown stype %r" % (stype,))


def zeros(stype, shape, ctx=None, dtype=None):
    from . import zeros as _zeros
    dense = _zeros(shape, ctx=ctx, dtype=dtype)
    return cast_storage(dense, stype)


def retain(data, indices):
    """Module-level sparse row retain (ref: mx.nd.sparse.retain →
    src/operator/tensor/sparse_retain.cc)."""
    if not isinstance(data, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    return data.retain(indices)


def _csr_rowids(indptr, nnz):
    """Row id of each stored element, from the CSR indptr — device-side
    (searchsorted over the monotonically increasing indptr)."""
    return jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1


def dot_csr_dense(values, col_indices, indptr, dense, num_rows,
                  transpose_lhs=False):
    """Device-native sparse-dense matmul on the CSR components — the
    O(nnz * n) kernel, no densification
    (ref: src/operator/tensor/dot-inl.h DotCsrDnsDns / DotCsrTransDnsDns).

    values [nnz], col_indices [nnz], indptr [m+1], dense [k, n].
    Returns [m, n] (or [k_cols, n] for transpose_lhs, where the CSR is
    contracted along its rows). Pure jnp: differentiable w.r.t. values
    and dense, jit/TPU-compatible (gather + segment_sum lower to XLA
    scatter-add)."""
    import jax
    nnz = values.shape[0]
    row_ids = _csr_rowids(indptr, nnz)
    cols = col_indices.astype(jnp.int32)
    if transpose_lhs:
        # out[c, :] += v_j * dense[row_j, :]  — contract over csr rows
        contrib = values[:, None] * dense[row_ids]
        return jax.ops.segment_sum(contrib, cols, num_segments=num_rows)
    # out[r, :] += v_j * dense[col_j, :]
    contrib = values[:, None] * dense[cols]
    return jax.ops.segment_sum(contrib, row_ids,
                               num_segments=num_rows)


from ..ops.registry import register as _register_op


@_register_op("_sparse_dot_csr_dense", num_inputs=2)
def _sparse_dot_csr_op(values, dense, col_indices=None, indptr=None,
                       num_rows=None, transpose_lhs=False,
                       swap_dense=False):
    """Registry seam for the CSR kernel: `values` and `dense` are the
    differentiable NDArray inputs (so autograd RECORDS the op and
    gradients flow to sparse values and dense weights); the integer
    CSR structure rides as static kwargs."""
    d = jnp.swapaxes(dense, -1, -2) if swap_dense else dense
    out = dot_csr_dense(values, col_indices, indptr, d, num_rows,
                        transpose_lhs=transpose_lhs)
    return jnp.swapaxes(out, -1, -2) if swap_dense else out


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """mx.nd.sparse.dot (ref: python/mxnet/ndarray/sparse.py dot,
    src/operator/tensor/dot.cc): CSR x dense (and transposes) run the
    device-native kernel above — autograd-recorded, so sparse feature
    matrices train; anything else falls back to the dense registry op."""
    from .register import invoke_by_name as _invoke
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, CSRNDArray):
        if transpose_b:
            raise NotImplementedError(
                "dot(csr, dense, transpose_b=True) is unsupported "
                "(matches the reference's dot.cc storage dispatch)")
        m, k = lhs.shape
        out_rows = k if transpose_a else m
        return _invoke("_sparse_dot_csr_dense", lhs.data, rhs,
                       col_indices=lhs.indices._data,
                       indptr=lhs.indptr._data, num_rows=out_rows,
                       transpose_lhs=transpose_a)
    if isinstance(rhs, CSRNDArray) and not isinstance(lhs, CSRNDArray):
        # dot(dense, csr) = dot(csr^T, dense^T)^T (2-D)
        if transpose_a:
            raise NotImplementedError(
                "dot(dense, csr, transpose_a=True) is unsupported")
        m, k = rhs.shape
        out_rows = m if transpose_b else k
        return _invoke("_sparse_dot_csr_dense", rhs.data, lhs,
                       col_indices=rhs.indices._data,
                       indptr=rhs.indptr._data, num_rows=out_rows,
                       transpose_lhs=not transpose_b, swap_dense=True)
    # dense x dense (or csr x csr, which densifies like the reference's
    # fallback storage path): the dense registry op, recorded as usual
    a = lhs.tostype("default") if isinstance(lhs, CSRNDArray) else lhs
    b = rhs.tostype("default") if isinstance(rhs, CSRNDArray) else rhs
    return _invoke("dot", a, b, transpose_a=transpose_a,
                   transpose_b=transpose_b)
