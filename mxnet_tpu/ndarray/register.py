"""Generate NDArray-level op wrappers from the functional registry.

Analog of the reference's import-time op wrapper generation
(ref: python/mxnet/ndarray/register.py, python/mxnet/_ctypes/ndarray.py
_imperative_invoke) and of Imperative::Invoke's dispatch
(ref: src/imperative/imperative.cc:89). Each call:

1. unwraps NDArray args to jax arrays,
2. threads PRNG keys / train-mode flags for ops that need them,
3. runs the pure function (XLA async-dispatches — the engine analog),
4. if autograd is recording and the outputs are differentiable, captures the
   ``jax.vjp`` closure on the tape (Imperative::RecordOp analog).
"""
from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp
import numpy as _np

from .. import autograd
from .. import random as _random
from ..ops import registry as _registry
from .ndarray import NDArray

__all__ = ["invoke", "invoke_by_name", "make_op_func", "populate",
           "invoke_getitem"]

_SPEC_CACHE = {}


def _spec(opdef):
    sp = _SPEC_CACHE.get(opdef.name)
    if sp is None:
        params = inspect.signature(opdef.fn).parameters
        sp = {
            "has_key": "key" in params,
            "has_training": "_training" in params,
        }
        _SPEC_CACHE[opdef.name] = sp
    return sp


from ..base import is_inexact_dtype as _is_inexact  # noqa: E402


# AMP input-cast hook (ref: python/mxnet/contrib/amp/amp.py:251 init —
# the reference rewrites every generated op wrapper at init; here one hook
# at the single dispatch choke point does the same job).
# Signature: hook(op_name, args, kwargs) -> (args, kwargs)
_amp_cast_hook = None
# bumped on every hook change; HybridBlock mixes it into its compile-cache
# key so graphs traced before amp.init() are not silently reused after
_amp_version = 0


def set_amp_cast_hook(hook):
    global _amp_cast_hook, _amp_version
    _amp_cast_hook = hook
    _amp_version += 1


def invoke(opdef, args, kwargs):
    spec = _spec(opdef)
    kwargs = dict(kwargs)
    if _amp_cast_hook is not None:
        args, kwargs = _amp_cast_hook(opdef.name, args, kwargs)
    if spec["has_key"] and kwargs.get("key") is None:
        kwargs["key"] = _random.next_key()
    if spec["has_training"] and "_training" not in kwargs:
        kwargs["_training"] = autograd.is_training()

    # collect differentiable NDArray inputs from args and kwargs
    arg_slots = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    kw_slots = [k for k, v in kwargs.items()
                if isinstance(v, NDArray) and k != "key"]
    nd_inputs = [args[i] for i in arg_slots] + [kwargs[k] for k in kw_slots]
    datas = tuple(a._data for a in nd_inputs)

    def fwd(*xs):
        new_args = list(args)
        new_kwargs = dict(kwargs)
        for slot, x in zip(arg_slots, xs[:len(arg_slots)]):
            new_args[slot] = x
        for k, x in zip(kw_slots, xs[len(arg_slots):]):
            new_kwargs[k] = x
        return opdef.fn(*new_args, **new_kwargs)

    recording = (autograd.is_recording() and not opdef.no_grad
                 and len(datas) > 0
                 and any(_is_inexact(d.dtype) for d in datas))
    if recording:
        out, vjp_fn = jax.vjp(fwd, *datas)
    else:
        out = fwd(*datas)

    multi = isinstance(out, (tuple, list))
    raw_outs = list(out) if multi else [out]
    outs = [NDArray(o) for o in raw_outs]

    if recording:
        if all(_is_inexact(o.dtype) for o in raw_outs):
            node = autograd.record_op(opdef.name, outs, nd_inputs, vjp_fn)
            node.fwd_fn = fwd
        # else: non-differentiable output — gradient stops here
    return tuple(outs) if multi else outs[0]


def invoke_by_name(name, *args, **kwargs):
    return invoke(_registry.get_op(name), args, kwargs)


def _as_data(v):
    return v._data if isinstance(v, NDArray) else v


def invoke_getitem(arr, key):
    """Basic+advanced indexing as a recorded op (differentiable gather)."""

    def fwd(x):
        return x[key]

    if autograd.is_recording() and _is_inexact(arr.dtype):
        out, vjp_fn = jax.vjp(fwd, arr._data)
        res = NDArray(out)
        node = autograd.record_op("getitem", [res], [arr], vjp_fn)
        node.fwd_fn = fwd
        return res
    return NDArray(fwd(arr._data))


def make_op_func(opdef, name):
    def op_func(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        res = invoke(opdef, args, kwargs)
        if out is None:
            return res
        # in-place result delivery (ref: generated wrappers' `out=` —
        # _imperative_invoke writes into the provided NDArray)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        ress = res if isinstance(res, (tuple, list)) else (res,)
        if len(outs) != len(ress):
            raise ValueError(
                "%s: out= has %d arrays but the op produces %d outputs"
                % (name, len(outs), len(ress)))
        for o, r in zip(outs, ress):
            if tuple(o.shape) != tuple(r.shape):
                raise ValueError(
                    "%s: out= array has shape %s but the result has "
                    "shape %s" % (name, tuple(o.shape), tuple(r.shape)))
            o._data = r._data.astype(o._data.dtype) \
                if r._data.dtype != o._data.dtype else r._data
        return out
    op_func.__name__ = name
    op_func.__doc__ = opdef.fn.__doc__
    return op_func


def populate(namespace_dict):
    """Install one wrapper per registered op name/alias into the module
    namespace (mirrors _init_op_module, ref: python/mxnet/ndarray/register.py)."""
    seen = {}
    for name in _registry.list_ops():
        opdef = _registry.get_op(name)
        if name not in namespace_dict:
            if id(opdef) not in seen:
                seen[id(opdef)] = make_op_func(opdef, opdef.name)
            fn = seen[id(opdef)]
            namespace_dict[name] = fn
