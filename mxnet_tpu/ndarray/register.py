"""Generate NDArray-level op wrappers from the functional registry.

Analog of the reference's import-time op wrapper generation
(ref: python/mxnet/ndarray/register.py, python/mxnet/_ctypes/ndarray.py
_imperative_invoke) and of Imperative::Invoke's dispatch
(ref: src/imperative/imperative.cc:89). Each call:

1. unwraps NDArray args to jax arrays,
2. threads PRNG keys / train-mode flags for ops that need them,
3. runs the pure function (XLA async-dispatches — the engine analog),
4. if autograd is recording and the outputs are differentiable, captures the
   ``jax.vjp`` closure on the tape (Imperative::RecordOp analog).

Imperative fast path (``MXNET_IMPERATIVE_JIT=1``, default on):

* **Jitted dispatch cache** — step 3 executes through a ``jax.jit``-compiled
  callable cached per (op name, static attr signature, input avals,
  AMP version), so repeated eager calls hit XLA's executable cache instead
  of dispatching primitive-by-primitive. A key is only compiled once it
  repeats (one-shot shapes stay on the eager path), mirroring how the
  reference only pays CachedOp setup for graphs that are reused. Under
  ``autograd.record()`` the jitted callable is the function ``jax.vjp``
  captures, so gradients flow through the compiled forward. Ops the
  registry marks in-place (``OpDef.inplace``, the ``req='write'`` analog)
  donate those input buffers to XLA on non-CPU backends. Unjittable ops
  (``OpDef.nojit``: host callbacks, data-dependent shapes) and calls whose
  attrs aren't hashable fall back to the untraced path.
* **Bulk segments** — inside ``engine.bulk(n)`` eligible ops are queued
  into a lazy segment and flushed as ONE jitted program at a sync point
  (``.asnumpy()``/buffer read, ``wait_for_var``/``wait_for_all``, autograd
  entry, or segment-full). This is the imperative CachedOp/bulking seam
  (ref: MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN, graph_executor.cc:1288
  InitOpSegs) applied to the eager layer.
"""
from __future__ import annotations

import inspect
import os
import threading
import time as _time
from weakref import ref as _wref

import jax
import numpy as _np

from .. import autograd
from .. import engine as _engine
from ..base import getenv as _getenv
from .. import profiler as _profiler
from .. import random as _random
from .. import storage as _storage
from .._debug import faultpoint as _faultpoint
from .._debug import flightrec as _flightrec
from .._debug import locktrace as _locktrace
from ..ops import registry as _registry
from .ndarray import NDArray, _PendingSlot

__all__ = ["invoke", "invoke_by_name", "make_op_func", "populate",
           "invoke_getitem", "imperative_jit_enabled", "set_imperative_jit",
           "dispatch_stats", "reset_dispatch_stats", "flush_bulk_segment",
           "bulk_segment_depth", "set_profiler_hooks", "aval",
           "register_signature_token", "signature_tokens",
           "signature_token_names"]

# Telemetry hooks at the dispatch choke points (the engine OprBlock hook
# analog, src/profiler/profiler.h:251). The per-op guard is the SHARED
# `_HOOKS and _profiler._LIVE` truth test: _LIVE covers both an active
# profile run and the always-on flight recorder (ISSUE 8) with ONE
# branch — when both are off the entire cost is two truth tests per op
# (BENCH_MODEL=profiler_overhead gates that at <2% of eager dispatch);
# with only the flight recorder on, the extra work is one bare-name
# ring append, no clock read (BENCH_MODEL=flightrec_overhead gates it
# at <0.5%).
# MXNET_PROFILER_HOOKS=0 removes even that (bench baseline / paranoia).
_HOOKS = _getenv("MXNET_PROFILER_HOOKS", "1") \
    not in ("0", "false", "off")

# Sentinel the shared guard yields when ONLY the flight recorder is on
# (_LIVE true, _ACTIVE false): the return sites discriminate on
# identity — `_prof_t0 is _FREC` → bare-name ring breadcrumb, any float
# → full profiler record. No clock read on the flightrec-only path.
_FREC = object()

# Allocation-ledger hot alias (ISSUE 13a): the bound deque.append for
# the 'activation' tag. The per-op registration is ONE
# `(weakref.ref(buf), op_name)` append — no callback, no nbytes read,
# no lock; liveness/size/total bookkeeping all happens at drain time on
# the memwatch/sampler daemons (storage.ledger_metrics). Sits inside
# the shared `_prof_t0 is not None` guard so the off path pays nothing;
# BENCH_MODEL=memory_overhead gates the pair at <0.5% of dispatch.
_LEDGER_ACT = _storage.pending_append("activation")


def set_profiler_hooks(enabled):
    """Toggle the profiler instrumentation guards at runtime (the env var
    ``MXNET_PROFILER_HOOKS`` sets the process default). Returns the
    previous value."""
    global _HOOKS
    prev = _HOOKS
    _HOOKS = bool(enabled)
    return prev

_SPEC_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic op on the dispatch hot path: a lock would cost more than the benign lost-update race; counters are best-effort, caches memoize deterministic values)


def _spec(opdef):
    sp = _SPEC_CACHE.get(opdef.name)
    if sp is None:
        params = inspect.signature(opdef.fn).parameters
        sp = {
            "has_key": "key" in params,
            "has_training": "_training" in params,
        }
        _SPEC_CACHE[opdef.name] = sp
    return sp


from ..base import is_inexact_dtype as _is_inexact  # noqa: E402


# AMP input-cast hook (ref: python/mxnet/contrib/amp/amp.py:251 init —
# the reference rewrites every generated op wrapper at init; here one hook
# at the single dispatch choke point does the same job).
# Signature: hook(op_name, args, kwargs) -> (args, kwargs)
_amp_cast_hook = None
# bumped on every hook change; HybridBlock mixes it into its compile-cache
# key so graphs traced before amp.init() are not silently reused after,
# and the imperative dispatch cache keys on it for the same reason
_amp_version = 0


def set_amp_cast_hook(hook):
    global _amp_cast_hook, _amp_version
    _amp_cast_hook = hook
    _amp_version += 1


# ---------------------------------------------------------------------------
# Jitted dispatch cache (fast path piece 1).
# ---------------------------------------------------------------------------

_JIT_ENABLED = _getenv("MXNET_IMPERATIVE_JIT", "1") \
    not in ("0", "false", "off")
# compile a key only once it repeats: one-shot (op, attrs, avals) combos —
# the norm in test sweeps — stay eager instead of paying a trace+compile
_JIT_THRESHOLD = 2
# full-clear bound so pathological shape churn can't grow without limit
# (the reference bounds CachedOp caches the same blunt way)
_CACHE_CAP = 8192

# mxlint: disable=MX003 (GIL-atomic memo of deterministic jitted callables; worst case a duplicate trace, never a wrong result)
_DISPATCH_CACHE = {}     # full key -> jitted callable
_KEY_COUNTS = {}         # full key -> times seen (for the hot threshold)  # mxlint: disable=MX003 (GIL-atomic heuristic counter: a lost update only delays compile-on-repeat by one call)
_PARTIAL_KEYS = set()    # (name, statics, amp) seen — retrace detection  # mxlint: disable=MX003 (GIL-atomic membership adds; retrace stat is best-effort)
_FAILED_KEYS = set()     # keys that raised under trace — permanent fallback  # mxlint: disable=MX003 (GIL-atomic adds; a racing miss just retries the trace once)

# observability (satellite: profiler counters; included in profiler.dumps)
# mxlint: disable=MX003 (GIL-atomic best-effort counters on the per-op hot path; the <2% overhead gate forbids a lock here)
_STATS = {
    "hits": 0,          # dispatch served by a cached jitted callable
    "misses": 0,        # key not yet compiled (eager while warming, or
                        # compiled this call)
    "retraces": 0,      # compile for an (op, attrs) seen before with
                        # different avals — shape/dtype churn indicator
    "fallbacks": 0,     # fast path enabled but call took the untraced path
    "bulk_flushes": 0,  # bulk segments executed as one program
    "bulk_ops": 0,      # ops that executed inside a bulk segment
    "bulk_fallbacks": 0,  # segment runners that raised and replayed
                          # eagerly (the 'eager-fallback' flush mode)
}


def imperative_jit_enabled():
    return _JIT_ENABLED


def set_imperative_jit(enabled):
    """Toggle the imperative fast path at runtime (the env var
    ``MXNET_IMPERATIVE_JIT`` sets the process default). Returns the
    previous value."""
    global _JIT_ENABLED
    prev = _JIT_ENABLED
    _JIT_ENABLED = bool(enabled)
    return prev


def dispatch_stats():
    """Snapshot of the dispatch-cache counters (hits/misses/retraces/
    fallbacks/bulk_flushes/bulk_ops)."""
    return dict(_STATS)


def reset_dispatch_stats():
    for k in _STATS:
        _STATS[k] = 0


def _clear_dispatch_cache():
    _DISPATCH_CACHE.clear()
    _KEY_COUNTS.clear()
    _PARTIAL_KEYS.clear()
    _FAILED_KEYS.clear()
    _AVAL_CACHE.clear()


_UNHASHABLE = object()


def _canon(v):
    """Canonicalize a static attr value into something hashable, or
    _UNHASHABLE to force the untraced path."""
    if v is None or isinstance(v, (str, bytes)):
        return v
    if isinstance(v, (bool, int, float, complex)):
        # the class is part of the key: 2 == 2.0 == True hash-collide, but
        # an int-2 closure and a float-2.0 closure promote dtypes
        # differently — replaying one for the other is silently wrong
        return (v.__class__, v)
    if isinstance(v, (list, tuple)):
        out = tuple(_canon(x) for x in v)
        return _UNHASHABLE if _UNHASHABLE in out else out
    if isinstance(v, dict):
        items = tuple(sorted((str(k), _canon(x)) for k, x in v.items()))
        return _UNHASHABLE if any(x is _UNHASHABLE for _, x in items) \
            else items
    if isinstance(v, _np.dtype):
        return str(v)
    if isinstance(v, _np.generic):
        return (str(v.dtype), v.item())
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        # array-like (NDArray/jax/np inside an attr): identity-hashable,
        # but its buffer can be rebound after the closure bakes it as a
        # constant — never cache on it
        return _UNHASHABLE
    try:
        hash(v)
    except TypeError:
        return _UNHASHABLE
    return v


def _aval(d):
    # np.dtype objects hash/compare by identity semantics and are cheap
    # key components; str(dtype) costs ~10us and is avoided on purpose
    return (d.shape, d.dtype, getattr(d, "weak_type", False))


def aval(d):
    """Hashable signature component for one jax array: (shape, dtype,
    weak_type). The shared key ingredient of every signature-keyed
    compile-on-repeat cache in the tree — the dispatch cache and bulk
    segments here, and the gluon fused train step
    (gluon/fused_step.py) — so they all discriminate inputs the same
    way."""
    return _aval(d)


def _snapshot(v):
    """Copy mutable attr containers so a queued bulk op is immune to the
    caller mutating them between queue and flush (the cache key was taken
    at queue time; the traced closure must see the same values)."""
    if isinstance(v, list):
        return [_snapshot(x) for x in v]
    if isinstance(v, tuple):
        return tuple(_snapshot(x) for x in v)
    if isinstance(v, dict):
        return {k: _snapshot(x) for k, x in v.items()}
    return v


def _build_traced(opdef, args, kwargs, arg_slots, kw_slots, take_key):
    """Build the pure positional-array function the jit/vjp machinery
    consumes. Statics are baked from THIS call (sound: the cache key pins
    them); NDArray slots are stripped so the cached closure never pins
    first-call buffers."""
    slot_set = set(arg_slots)
    s_args = [None if i in slot_set else a for i, a in enumerate(args)]
    kw_set = set(kw_slots)
    s_kwargs = {k: (None if (k in kw_set or (take_key and k == "key"))
                    else v) for k, v in kwargs.items()}
    n_args = len(arg_slots)
    n_kw = len(kw_slots)
    fn = opdef.fn

    def traced(*xs):
        new_args = list(s_args)
        new_kwargs = dict(s_kwargs)
        for slot, x in zip(arg_slots, xs[:n_args]):
            new_args[slot] = x
        for k, x in zip(kw_slots, xs[n_args:n_args + n_kw]):
            new_kwargs[k] = x
        if take_key:
            new_kwargs["key"] = xs[-1]
        return fn(*new_args, **new_kwargs)

    return traced


def _donate_argnums(opdef, arg_slots, recording):
    """Map OpDef.inplace (positional tensor-input indices) onto positions
    in the traced-arg tuple. Donation is a pure buffer-reuse hint to XLA:
    only meaningful off-CPU, never while recording (residuals alias
    inputs)."""
    if not opdef.inplace or recording:
        return ()
    try:
        if jax.default_backend() == "cpu":
            return ()  # donation is a no-op on CPU; skip the warning
    except Exception:
        return ()
    donate = []
    for idx in opdef.inplace:
        try:
            donate.append(arg_slots.index(idx))
        except ValueError:
            pass  # in-place input passed as kwarg/static — skip
    return tuple(donate)


def _cached_callable(opdef, key, partial_key, args, kwargs, arg_slots,
                     kw_slots, take_key, recording):
    """Return the jitted callable for ``key``, compiling it once the key
    has repeated (_JIT_THRESHOLD), or None while warming."""
    fn = _DISPATCH_CACHE.get(key)
    if fn is not None:
        _STATS["hits"] += 1
        return fn
    _STATS["misses"] += 1
    if len(_KEY_COUNTS) >= 4 * _CACHE_CAP:
        _KEY_COUNTS.clear()  # one-shot keys (shape churn) must not leak
    seen = _KEY_COUNTS.get(key, 0) + 1
    _KEY_COUNTS[key] = seen
    if seen < _JIT_THRESHOLD:
        return None
    if len(_DISPATCH_CACHE) >= _CACHE_CAP:
        _clear_dispatch_cache()
    if partial_key in _PARTIAL_KEYS:
        _STATS["retraces"] += 1
    _PARTIAL_KEYS.add(partial_key)
    traced = _build_traced(opdef, args, kwargs, arg_slots, kw_slots,
                           take_key)
    donate = _donate_argnums(opdef, arg_slots, recording)
    if _locktrace.ENABLED:
        # the first call of this jitted fn traces + compiles (seconds):
        # a framework lock held here starves every other thread
        _locktrace.boundary("imperative.jit_compile")
    if _faultpoint.ACTIVE:
        # compile-site fault seam: a raise here is caught by invoke(),
        # which marks the key permanently failed and dispatches eagerly
        # — the same degradation a real jax.jit construction error takes
        _faultpoint.check("imperative.jit.compile")
    fn = jax.jit(traced, donate_argnums=donate) if donate \
        else jax.jit(traced)
    probe = _compile_probe(opdef, key, fn)
    _DISPATCH_CACHE[key] = probe
    return probe


def _sig_repr(key):
    """Compact human-readable form of a dispatch-cache key's avals for
    the compile-attribution registry (shape churn reads as the same
    name with a changing key)."""
    avals = key[-1]
    try:
        return ",".join("%s%s" % (_np.dtype(dt).name, list(shape))
                        for shape, dt, _w in avals)
    except Exception:
        return repr(avals)[:80]


def _compile_probe(opdef, key, fn):
    """One-shot wrapper timing the FIRST call of a fresh jitted
    callable — trace + XLA compile + first run — into the compile-
    attribution registry (profiler.record_compile, ISSUE 8c), then
    unwraps itself from the dispatch cache so every later hit pays
    nothing. Compiles are rare and expensive: they are recorded
    unconditionally (the ``account`` contract), not only under a
    profile run."""
    def probe(*xs):
        t0 = _time.perf_counter()
        out = fn(*xs)
        if _DISPATCH_CACHE.get(key) is probe:
            _DISPATCH_CACHE[key] = fn
        _profiler.record_compile("imperative:%s" % opdef.name,
                                 key=_sig_repr(key),
                                 dur_us=(_time.perf_counter() - t0) * 1e6)
        return out
    return probe


def _record_invoke(opdef, t0):
    # mxlint: disable=MX002 (called only when _prof_t0 is not None, i.e. under the inlined `_HOOKS and _ACTIVE` guard at both call sites — keeping the guard expression inline there is the whole point)
    _profiler.record_op(opdef.name, (_time.perf_counter() - t0) * 1e6,
                        category="operator", lane="imperative")


def invoke(opdef, args, kwargs):
    # telemetry guard is inlined (no wrapper call) and SHARED between
    # the profiler and the always-on flight recorder (_LIVE, ISSUE 8):
    # with both off the whole cost is this one conditional plus two
    # `is not None` tests at the return sites. With only the flight
    # recorder on, the guard yields the _FREC sentinel instead of a
    # timestamp — no clock read — and the return sites append ONE bare
    # op-name breadcrumb to the ring (dump-time rendering anchors it to
    # the nearest timestamped neighbor). A perf_counter pair alone
    # costs ~3x the flightrec budget per op, which is why the
    # flightrec-only path records order, not durations
    # (BENCH_MODEL=profiler_overhead / flightrec_overhead gate both).
    _prof_t0 = (_time.perf_counter() if _profiler._ACTIVE else _FREC) \
        if (_HOOKS and _profiler._LIVE) else None
    spec = _spec(opdef)
    if _amp_cast_hook is not None or spec["has_key"] or spec["has_training"]:
        kwargs = dict(kwargs)
        if _amp_cast_hook is not None:
            args, kwargs = _amp_cast_hook(opdef.name, args, kwargs)
        if spec["has_key"] and kwargs.get("key") is None:
            kwargs["key"] = _random.next_key()
        if spec["has_training"] and "_training" not in kwargs:
            kwargs["_training"] = autograd.is_training()

    # collect differentiable NDArray inputs from args and kwargs
    arg_slots = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    if kwargs:
        kw_slots = [k for k, v in kwargs.items()
                    if isinstance(v, NDArray) and k != "key"]
        nd_inputs = [args[i] for i in arg_slots] \
            + [kwargs[k] for k in kw_slots]
    else:
        kw_slots = []
        nd_inputs = [args[i] for i in arg_slots] \
            if len(arg_slots) != len(args) else list(args)

    fast_ok = _JIT_ENABLED and not opdef.nojit
    recording = autograd.is_recording()

    # -- bulk segment (fast path piece 2): queue instead of executing ----
    # (NaiveEngine is checked once at engine.bulk entry, not per op)
    if fast_ok and not recording:
        seg = getattr(_BULK_LOCAL, "segment", None)
        if seg is not None:
            out = seg.try_queue(opdef, spec, args, kwargs, arg_slots,
                                kw_slots, nd_inputs)
            if out is not _NOT_BULKED:
                if _prof_t0 is not None:
                    if _prof_t0 is _FREC:
                        # flight-recorder-only path: bare-name ring
                        # append, inlined — even a helper call or one
                        # clock read would breach the <0.5%-of-dispatch
                        # budget
                        _flightrec.RING.append(opdef.name)
                    else:
                        _record_invoke(opdef, _prof_t0)
                return out

    datas = tuple(a._data for a in nd_inputs)

    recording = (recording and not opdef.no_grad
                 and len(datas) > 0
                 and any(_is_inexact(d.dtype) for d in datas))

    # PRNG key: a per-call jax array. The jitted path must take it as a
    # traced argument — a closure-captured key would be baked into the
    # compiled executable as a constant and every later hit would silently
    # reuse the first call's randomness.
    if spec["has_key"]:
        key_val = kwargs.get("key")
        if isinstance(key_val, NDArray):
            key_val = key_val._data
        take_key = key_val is not None and hasattr(key_val, "dtype")
    else:
        key_val = None
        take_key = False

    jfn = None
    if fast_ok:
        key, partial_key = _dispatch_key(opdef, args, kwargs, arg_slots,
                                         kw_slots, datas, key_val, take_key,
                                         recording)
        if key is not None and key not in _FAILED_KEYS:
            try:
                jfn = _cached_callable(opdef, key, partial_key, args,
                                       kwargs, arg_slots, kw_slots,
                                       take_key, recording)
            except Exception:
                # jax.jit construction failed (bad donation spec, or an
                # injected imperative.jit.compile fault): permanent
                # eager fallback for this key — never a crash. Before
                # this guard a constructor error propagated to the user
                # even though the eager path was perfectly able to run.
                if len(_FAILED_KEYS) >= _CACHE_CAP:
                    _FAILED_KEYS.clear()
                _FAILED_KEYS.add(key)
                _DISPATCH_CACHE.pop(key, None)
                _STATS["fallbacks"] += 1
                jfn = None
        else:
            _STATS["fallbacks"] += 1
    elif _JIT_ENABLED and opdef.nojit:
        _STATS["fallbacks"] += 1  # registry opt-out (host callback etc.)

    fwd = None
    out = _PENDING_SENTINEL
    vjp_fn = None
    if jfn is not None:
        jit_fwd = (lambda *xs: jfn(*xs, key_val)) if take_key else jfn
        try:
            if recording:
                out, vjp_fn = jax.vjp(jit_fwd, *datas)
            else:
                out = jit_fwd(*datas)
        except Exception:
            # trace-incompatible op (concretization, host callback, ...):
            # remember the key and re-run the genuine eager path below so
            # real errors surface from untraced execution
            if len(_FAILED_KEYS) >= _CACHE_CAP:
                _FAILED_KEYS.clear()  # shape churn must not leak keys
            _FAILED_KEYS.add(key)
            _DISPATCH_CACHE.pop(key, None)
            _STATS["fallbacks"] += 1
            out = _PENDING_SENTINEL
        else:
            fwd = jit_fwd  # the tape replays through the compiled forward

    if out is _PENDING_SENTINEL:
        def fwd(*xs):
            new_args = list(args)
            new_kwargs = dict(kwargs)
            for slot, x in zip(arg_slots, xs[:len(arg_slots)]):
                new_args[slot] = x
            for k, x in zip(kw_slots, xs[len(arg_slots):]):
                new_kwargs[k] = x
            return opdef.fn(*new_args, **new_kwargs)

        if recording:
            out, vjp_fn = jax.vjp(fwd, *datas)
        else:
            out = fwd(*datas)

    multi = isinstance(out, (tuple, list))
    raw_outs = list(out) if multi else [out]
    # NaiveEngine forced sync: errors surface at the faulting op
    # (ref: src/engine/naive_engine.cc serial debugging mode)
    if _engine.is_naive():
        _engine.maybe_sync(raw_outs)
    outs = [NDArray(o) for o in raw_outs]

    if recording:
        if all(_is_inexact(o.dtype) for o in raw_outs):
            node = autograd.record_op(opdef.name, outs, nd_inputs, vjp_fn)
            node.fwd_fn = fwd
        # else: non-differentiable output — gradient stops here
    if _prof_t0 is not None:
        if _prof_t0 is _FREC:
            # flight-recorder-only path: see the bulk return site above
            _flightrec.RING.append(opdef.name)
        else:
            _record_invoke(opdef, _prof_t0)
        if _storage._LEDGER_ON:
            # tag every fresh eager result 'activation' in the
            # allocation ledger; the op name doubles as the site label
            for _o in raw_outs:
                _LEDGER_ACT((_wref(_o), opdef.name))
    return tuple(outs) if multi else outs[0]


# ---------------------------------------------------------------------------
# Compile-signature token registry.
#
# Env vars whose VALUE changes a traced graph (Pallas kernel routing,
# the packed optimizer apply) are exactly the ambient state the PR 9
# review pass caught leaking into cached executables: a hot signature
# silently replayed the pre-flip path until the kernel envs joined the
# dispatch key. The registry formalizes that fix — register a var here
# and its current value joins EVERY compile-cache signature (the
# imperative dispatch key below AND gluon/fused_step's program key), so
# flipping it mid-process recompiles instead of replaying stale code.
# mxlint MX014 closes the loop statically: an env read reachable from a
# trace entry point must name a registered token (or carry a waiver).
# ---------------------------------------------------------------------------

# [(name, default)] in registration order
_SIG_TOKENS = []  # mxlint: disable=MX003 (appended at import/plugin-registration time only, which serializes under the import lock / lib_api load lock; key builds only iterate)


def register_signature_token(name, default=""):
    """Register an env var as part of every compile-cache signature.
    Idempotent per name; returns the name so modules can do
    ``_ENV = register_signature_token("MXTPU_X", "1")``."""
    for n, _ in _SIG_TOKENS:
        if n == name:
            return name
    _SIG_TOKENS.append((str(name), str(default)))
    return name


def signature_token_names():
    """Registered token names, registration order (doc/lint surface)."""
    return tuple(n for n, _ in _SIG_TOKENS)


def signature_tokens():
    """Current values of every registered token, as one hashable tuple.
    Both cache-key builders consume this: a handful of dict lookups per
    key build, far below the aval hashing already paid."""
    # mxlint: disable=MX015 (the registry's own read loop: every name here came through register_signature_token, which MX015 doc-checks individually)
    return tuple(_getenv(n, d) for n, d in _SIG_TOKENS)


# The kernel-routing switches (ops/nn.py batch_norm, ops/quantized.py,
# the global kill switch) and the packed-apply/autotune toggles that
# change traced update/kernel graphs. New env-routed kernels register
# theirs alongside these.
register_signature_token("MXTPU_NO_PALLAS", "0")
register_signature_token("MXTPU_FUSED_BN", "1")
register_signature_token("MXTPU_QUANT_MATMUL", "1")
register_signature_token("MXTPU_FUSED_APPLY", "0")
register_signature_token("MXTPU_FLASH_AUTOTUNE", "0")
# the packed-apply bucket plan (parallel/overlap.bucket_plan) reads the
# bucket-size cap at trace time, so it shapes the traced update graph —
# found by mxlint MX014 on its first whole-tree run (exactly the PR 9
# stale-replay class: flip the cap mid-run, replay the old bucketing)
register_signature_token("MXTPU_ELASTIC_BUCKET_MB", "4")
# training-health sentinels (ISSUE 15): MXTPU_HEALTH threads the
# summary/corruption operands through the fused-step program, and the
# skip_step/halt actions add the in-graph discard select — both change
# the traced graph, so flipping either must retrace, never replay
register_signature_token("MXTPU_HEALTH", "0")
register_signature_token("MXTPU_HEALTH_ACTION", "record")
# 3D-parallel trainer path (docs/PARALLEL.md): the chunked-CE
# local-accumulation auto-select (parallel/transformer.loss_fn) and the
# fused step's GSPMD mesh mode (gluon/fused_step.py) both branch the
# traced graph on these at trace time — flipping either mid-run must
# land on a fresh cache key, never replay the other program
register_signature_token("MXTPU_CE_LOCAL_ACCUM", "auto")
register_signature_token("MXTPU_GSPMD_STEP", "1")
# zero-badput legs (ISSUE 19): the persistent AOT compile cache keys
# serialized executables by the FULL token-registry snapshot, so every
# switch that gates one of the three legs must itself be a token — a
# cache entry written under one setting can then never be replayed
# under another (the same stale-replay class MX014 polices for traced
# graphs, applied to on-disk executables)
register_signature_token("MXTPU_CKPT_ASYNC", "0")
register_signature_token("MXTPU_CKPT_DELTA", "0")
register_signature_token("MXTPU_COMPILE_CACHE_DIR", "")
register_signature_token("MXTPU_PEER_RESTORE", "0")
# control-plane survivability legs (ISSUE 20): none of these shape a
# traced graph, but each changes what recovery/resume semantics a
# process commits to (journaled vs in-memory server state, fenced vs
# unfenced writes, drain-vs-die on SIGTERM, single vs chained
# endpoints) — a resumed or cache-replayed run must agree with the run
# that wrote its artifacts, so they ride the same registry snapshot the
# ISSUE 19 knobs do
register_signature_token("MXTPU_PS_JOURNAL_DIR", "")
register_signature_token("MXTPU_PS_ENDPOINTS", "")
register_signature_token("MXTPU_PS_FENCING", "0")
register_signature_token("MXTPU_PREEMPT_GRACE_S", "0")

# back-compat spelling (PR 9 introduced the kernel-env tuple under this
# name; the registry supersedes it)
_kernel_env_token = signature_tokens


def _dispatch_key(opdef, args, kwargs, arg_slots, kw_slots, datas, key_val,
                  take_key, recording):
    """(full cache key, partial key) or (None, None) if unhashable."""
    if len(arg_slots) == len(args) and not kwargs:
        statics = ()  # hot case: pure tensor call, no attrs
    else:
        statics = []
        slot_set = set(arg_slots)
        for i, a in enumerate(args):
            if i not in slot_set:
                c = _canon(a)
                if c is _UNHASHABLE:
                    return None, None
                statics.append((i, c))
        kw_set = set(kw_slots)
        for k in sorted(kwargs):
            if k in kw_set or (take_key and k == "key"):
                continue
            c = _canon(kwargs[k])
            if c is _UNHASHABLE:
                return None, None
            statics.append((k, c))
        statics = tuple(statics)
    avals = tuple(_aval(d) for d in datas)
    if take_key:
        avals = avals + (_aval(key_val),)
    partial = (opdef.name, statics, tuple(arg_slots), tuple(kw_slots),
               _amp_version, recording, signature_tokens())
    return partial + (avals,), partial


def invoke_by_name(name, *args, **kwargs):
    return invoke(_registry.get_op(name), args, kwargs)


def _as_data(v):
    return v._data if isinstance(v, NDArray) else v


def invoke_getitem(arr, key):
    """Basic+advanced indexing as a recorded op (differentiable gather)."""

    def fwd(x):
        return x[key]

    if autograd.is_recording() and _is_inexact(arr.dtype):
        out, vjp_fn = jax.vjp(fwd, arr._data)
        res = NDArray(out)
        node = autograd.record_op("getitem", [res], [arr], vjp_fn)
        node.fwd_fn = fwd
        return res
    return NDArray(fwd(arr._data))


# ---------------------------------------------------------------------------
# Bulk segments (fast path piece 2): engine.bulk's lazy op accumulator.
# ---------------------------------------------------------------------------

_PENDING_SENTINEL = object()
_NOT_BULKED = object()
_BULK_LOCAL = threading.local()

# out-aval cache: (name, statics, in avals) -> tuple of (shape, dtype)
_AVAL_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic memo of eval_shape results: deterministic, duplicate compute is the worst case)


def bulk_segment_depth():
    """Number of ops currently queued in this thread's bulk segment."""
    seg = getattr(_BULK_LOCAL, "segment", None)
    return len(seg.ops) if seg is not None else 0


def begin_bulk_segment(limit):
    """Install a fresh bulk segment for this thread (engine.bulk enter).
    Any previously active segment is flushed first, so cross-segment
    dataflow can never arise; it is restored (empty) when this one ends,
    so nested engine.bulk scopes compose."""
    flush_bulk_segment()
    seg = _BulkSegment(max(1, int(limit)))
    seg.prev = getattr(_BULK_LOCAL, "segment", None)
    _BULK_LOCAL.segment = seg
    return seg


def end_bulk_segment(seg=None):
    """Flush and deactivate the current segment (engine.bulk exit). The
    segment is deactivated even if the flush raises — a zombie segment
    would silently keep queueing every later op on this thread."""
    cur = getattr(_BULK_LOCAL, "segment", None)
    try:
        if cur is not None:
            cur.flush()
    finally:
        _BULK_LOCAL.segment = getattr(seg or cur, "prev", None)


def flush_bulk_segment():
    """Drain this thread's pending bulk segment (sync points: wait_for_all,
    wait_for_var, autograd.backward, engine.set_bulk_size)."""
    cur = getattr(_BULK_LOCAL, "segment", None)
    if cur is not None:
        cur.flush()


def set_active_bulk_limit(limit):
    """Apply a mid-scope engine.set_bulk_size to the live segment (the
    flush already happened; future ops must honor the new cap)."""
    cur = getattr(_BULK_LOCAL, "segment", None)
    if cur is not None:
        cur.limit = max(1, int(limit))


# runner cache: segment signature -> jitted program over the leaf arrays
_SEGMENT_CACHE = {}  # mxlint: disable=MX003 (GIL-atomic memo of jitted segment runners, same contract as _DISPATCH_CACHE)
_SEGMENT_COUNTS = {}  # signature -> times flushed (compile-on-repeat)  # mxlint: disable=MX003 (GIL-atomic heuristic counter, see _KEY_COUNTS)


def deliver_result(dst, src):
    """dst NDArray <- src NDArray's value, preserving dst's dtype (the
    out=/state-writeback delivery contract). A still-pending bulk result
    with matching dtype is ADOPTED — dst resolves at the segment flush —
    instead of forcing a per-op flush."""
    rb = src._buf
    if type(rb) is _PendingSlot and dst.dtype == src.dtype \
            and isinstance(rb.segment, _BulkSegment):
        rb.segment.adopt(dst, rb)
        dst._buf = rb
    else:
        d = src._data
        dst._data = d.astype(dst._data.dtype) \
            if d.dtype != dst._data.dtype else d
    return dst


class _BulkSegment:
    """Accumulates eager op thunks; flushes them as ONE jitted XLA program
    (the CachedOp/InitOpSegs analog for the imperative layer)."""

    def __init__(self, limit):
        self.limit = limit
        self.ops = []        # (opdef.name, statics, in_refs, call, multi)
        self.leaves = []     # concrete jax arrays feeding the segment
        self.leaf_ids = {}   # id(jax array) -> leaf index
        self.outs = []       # (ndarray, placeholder, op_idx, out_idx)
        self.prev = None     # outer segment to restore on scope exit

    def adopt(self, arr, slot):
        """Register an extra NDArray to receive ``slot``'s result at flush
        (out= delivery aliasing a still-pending output)."""
        self.outs.append((arr, slot, slot.ref[1], slot.ref[2]))

    def try_queue(self, opdef, spec, args, kwargs, arg_slots, kw_slots,
                  nd_inputs):
        """Queue the op if it is bulkable; _NOT_BULKED otherwise."""
        key_val = kwargs.get("key") if spec["has_key"] else None
        if isinstance(key_val, NDArray):
            key_val = key_val._data
        take_key = key_val is not None and hasattr(key_val, "dtype")

        # statics must be hashable (they key the cached runner)
        key, _partial = _dispatch_key(opdef, args, kwargs, arg_slots,
                                      kw_slots, (), key_val, take_key,
                                      False)
        if key is None or opdef.name in _BULK_FAILED_OPS:
            return _NOT_BULKED
        statics = key[:-1]

        # resolve traced inputs: pending refs from THIS segment chain
        # lazily; anything else becomes a concrete leaf. New leaves are
        # STAGED and only committed once the op is definitely queued —
        # a bail-out must not leave orphan leaves that perturb the
        # segment signature (spurious runner recompiles).
        staged = []       # jax arrays not yet in self.leaves
        staged_ids = {}   # id -> provisional leaf index

        def leaf_ref(buf):
            idx = self.leaf_ids.get(id(buf))
            if idx is None:
                idx = staged_ids.get(id(buf))
                if idx is None:
                    idx = len(self.leaves) + len(staged)
                    staged.append(buf)
                    staged_ids[id(buf)] = idx
            return ("l", idx)

        in_refs = []
        in_avals = []
        bufs = [a._buf for a in nd_inputs]
        for buf in bufs:
            if type(buf) is _PendingSlot:
                if buf.segment is not self:
                    buf.segment.flush()  # foreign segment: materialize
                    return _NOT_BULKED
                in_refs.append(buf.ref)
                in_avals.append((buf.shape, buf.dtype, False))
            else:
                in_refs.append(leaf_ref(buf))
                in_avals.append(_aval(buf))
        if take_key:
            in_refs.append(leaf_ref(key_val))
            in_avals.append(_aval(key_val))

        # attr containers are snapshotted: the runner cache is keyed on
        # their queue-time values, so the flush-time closure must be
        # immune to the caller mutating them in between
        slot_set = set(arg_slots)
        s_args = tuple(a if i in slot_set else _snapshot(a)
                       for i, a in enumerate(args))
        kw_set = set(kw_slots)
        s_kwargs = {k: (v if (k in kw_set or k == "key") else _snapshot(v))
                    for k, v in kwargs.items()}
        # the traced closure itself is built lazily at flush time, only
        # when the segment-runner cache misses
        call = (opdef, s_args, s_kwargs, tuple(arg_slots), tuple(kw_slots),
                take_key)

        # output avals via abstract eval (cached per op+statics+avals)
        aval_key = (opdef.name, statics, tuple(in_avals))
        out_avals = _AVAL_CACHE.get(aval_key)
        if out_avals is None:
            structs = [jax.ShapeDtypeStruct(tuple(shape), _np.dtype(dtype))
                       for shape, dtype, _w in in_avals]
            try:
                shaped = jax.eval_shape(_build_traced(*call), *structs)
            except Exception:
                _BULK_FAILED_OPS.add(opdef.name)
                return _NOT_BULKED
            multi = isinstance(shaped, (tuple, list))
            out_avals = (bool(multi),
                         tuple((tuple(s.shape), s.dtype)
                               for s in (shaped if multi else [shaped])))
            if len(_AVAL_CACHE) >= _CACHE_CAP:
                _AVAL_CACHE.clear()
            _AVAL_CACHE[aval_key] = out_avals

        for buf in staged:
            self.leaf_ids[id(buf)] = len(self.leaves)
            self.leaves.append(buf)

        multi, shapes = out_avals
        op_idx = len(self.ops)
        self.ops.append((opdef.name, statics, tuple(in_refs), call, multi))
        outs = []
        for out_idx, (shape, dtype) in enumerate(shapes):
            slot = _PendingSlot(self, shape, dtype, ("o", op_idx, out_idx))
            arr = NDArray(slot)
            self.outs.append((arr, slot, op_idx, out_idx))
            outs.append(arr)
        _STATS["bulk_ops"] += 1
        if len(self.ops) >= self.limit:
            self.flush()
        return tuple(outs) if multi else outs[0]

    def flush(self):
        """Execute all queued ops as one jitted program and deliver the
        results onto their NDArrays. When profiling is on, the flush is a
        span in the ``bulk`` lane carrying the op count and whether this
        segment compiled, replayed a cached program, or ran eagerly — and
        a memory sample lands at the boundary (allocation churn point)."""
        if not self.ops:
            return
        if _HOOKS and _profiler._LIVE:
            n_ops = len(self.ops)
            t0 = _time.perf_counter()
            mode = self._flush_impl()
            _profiler.record_op(
                "bulk_segment", (_time.perf_counter() - t0) * 1e6,
                category="bulk", lane="bulk",
                args={"ops": n_ops, "mode": mode})
            _profiler.sample_memory("bulk_flush")
        else:
            self._flush_impl()

    def _flush_impl(self):
        """Returns how the segment executed: ``cached`` (jitted runner
        hit), ``compile`` (runner traced+compiled this flush),
        ``eager-warming`` (signature below the compile-on-repeat
        threshold), or ``eager-fallback`` (runner raised; replayed
        untraced)."""
        ops, leaves, outs = self.ops, self.leaves, self.outs
        self.ops, self.leaves, self.outs = [], [], []
        self.leaf_ids = {}

        sig = (tuple((name, statics, in_refs, multi)
                     for name, statics, in_refs, _call, multi in ops),
               tuple(_aval(l) for l in leaves))
        mode = "cached"
        runner = _SEGMENT_CACHE.get(sig)
        if runner is None:
            # compile-on-repeat, like the dispatch cache: a signature seen
            # once (e.g. a per-step lr schedule baking a fresh scalar into
            # every segment) replays eagerly instead of paying a whole-
            # segment trace+compile per flush
            if len(_SEGMENT_COUNTS) >= 4 * _CACHE_CAP:
                _SEGMENT_COUNTS.clear()
            seen = _SEGMENT_COUNTS.get(sig, 0) + 1
            _SEGMENT_COUNTS[sig] = seen
            if seen < _JIT_THRESHOLD:
                self._replay_eager(ops, leaves, outs)
                _STATS["bulk_flushes"] += 1
                return "eager-warming"
            if len(_SEGMENT_CACHE) >= _CACHE_CAP:
                _SEGMENT_CACHE.clear()
            spec = [(_build_traced(*call), in_refs, multi)
                    for _name, _statics, in_refs, call, multi in ops]

            def run(leaf_vals):
                results = []
                for fn, in_refs, multi in spec:
                    ins = [leaf_vals[r[1]] if r[0] == "l"
                           else results[r[1]][r[2]] for r in in_refs]
                    o = fn(*ins)
                    results.append(tuple(o) if multi else (o,))
                return results

            if _locktrace.ENABLED:
                _locktrace.boundary("imperative.bulk_compile")
            runner = jax.jit(run)
            _SEGMENT_CACHE[sig] = runner
            mode = "compile"

        try:
            if _faultpoint.ACTIVE and mode == "compile":
                # compile-site fault seam: drives the eager-fallback
                # replay below, exactly like a real trace failure (the
                # runner stays cached — a later flush of the same
                # signature replays it, mirroring a transient failure)
                _faultpoint.check("engine.bulk.compile")
            c0 = _time.perf_counter() if mode == "compile" else None
            results = runner(leaves)
        except Exception:
            # a queued op turned out to be unjittable: replay the segment
            # eagerly in order so results (and real errors) match the
            # untraced path, and stop bulking the offending ops
            self._replay_eager(ops, leaves, outs, blacklist=True)
            _STATS["bulk_flushes"] += 1
            _STATS["bulk_fallbacks"] += 1
            return "eager-fallback"
        if c0 is not None:
            # compile-attribution span (ISSUE 8): the first run of a
            # fresh segment runner = trace + XLA compile + execute
            _profiler.record_compile(
                "bulk_segment", key="%d ops" % len(ops),
                dur_us=(_time.perf_counter() - c0) * 1e6)
        _STATS["bulk_flushes"] += 1
        for arr, slot, i, k in outs:
            if arr._buf is slot:  # not overwritten since queueing
                arr._buf = results[i][k]
        if _HOOKS and _profiler._LIVE and _storage._LEDGER_ON:
            # bulk-segment leaves deliver here, not at invoke (their
            # outputs were pending slots then): one ledger append per
            # delivered result, tagged with the producing op's name
            for _arr, _slot, i, k in outs:
                _LEDGER_ACT((_wref(results[i][k]), ops[i][0]))
        return mode

    @staticmethod
    def _replay_eager(ops, leaves, outs, blacklist=False):
        """Execute a popped segment op-by-op (untraced) and deliver the
        results. On an op failure, completed results are still delivered;
        arrays at/after the faulting op are re-homed to a dead segment so
        a caught exception can never let their stale op-indices resolve
        against a future batch (they raise on read instead)."""
        results = []
        try:
            for name, _statics, in_refs, call, multi in ops:
                fn = _build_traced(*call)
                ins = [leaves[r[1]] if r[0] == "l"
                       else results[r[1]][r[2]] for r in in_refs]
                try:
                    o = fn(*ins)
                except Exception:
                    if blacklist:
                        _BULK_FAILED_OPS.add(name)
                    raise
                results.append(tuple(o) if multi else (o,))
        finally:
            ledger = _HOOKS and _profiler._LIVE and _storage._LEDGER_ON
            for arr, slot, i, k in outs:
                if i < len(results) and arr._buf is slot:
                    arr._buf = results[i][k]
                    if ledger:
                        _LEDGER_ACT((_wref(results[i][k]), ops[i][0]))
                elif arr._buf is slot:
                    slot.segment = _FAILED_SEGMENT


_BULK_FAILED_OPS = set()  # mxlint: disable=MX003 (GIL-atomic adds; a racing miss re-queues one doomed op which then fails over identically)


class _DeadSegment:
    """Home of _PendingSlots whose producing flush failed: flush is a
    no-op, so NDArray._data finds the slot still pending and raises."""

    def flush(self):
        pass


_FAILED_SEGMENT = _DeadSegment()


def make_op_func(opdef, name):
    def op_func(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        res = invoke(opdef, args, kwargs)
        if out is None:
            return res
        # in-place result delivery (ref: generated wrappers' `out=` —
        # _imperative_invoke writes into the provided NDArray)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        ress = res if isinstance(res, (tuple, list)) else (res,)
        if len(outs) != len(ress):
            raise ValueError(
                "%s: out= has %d arrays but the op produces %d outputs"
                % (name, len(outs), len(ress)))
        for o, r in zip(outs, ress):
            if tuple(o.shape) != tuple(r.shape):
                raise ValueError(
                    "%s: out= array has shape %s but the result has "
                    "shape %s" % (name, tuple(o.shape), tuple(r.shape)))
            # shape/dtype peeks don't flush; deliver_result adopts a
            # still-pending bulk result instead of forcing a flush
            deliver_result(o, r)
        return out
    op_func.__name__ = name
    op_func.__doc__ = opdef.fn.__doc__
    return op_func


def populate(namespace_dict):
    """Install one wrapper per registered op name/alias into the module
    namespace (mirrors _init_op_module, ref: python/mxnet/ndarray/register.py)."""
    seen = {}
    for name in _registry.list_ops():
        opdef = _registry.get_op(name)
        if name not in namespace_dict:
            if id(opdef) not in seen:
                seen[id(opdef)] = make_op_func(opdef, opdef.name)
            fn = seen[id(opdef)]
            namespace_dict[name] = fn
