"""``mx.nd.random`` namespace (ref: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .register import invoke_by_name as _inv

__all__ = ["uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "multinomial",
           "randint", "shuffle"]


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .ndarray import NDArray
    if isinstance(low, NDArray):
        return _inv("sample_uniform", low, high, shape=_shape(shape), dtype=dtype)
    return _inv("random_uniform", low=low, high=high, shape=_shape(shape),
                dtype=dtype)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .ndarray import NDArray
    if isinstance(loc, NDArray):
        return _inv("sample_normal", loc, scale, shape=_shape(shape), dtype=dtype)
    return _inv("random_normal", loc=loc, scale=scale, shape=_shape(shape),
                dtype=dtype)


def randn(*shape, **kwargs):
    return normal(shape=shape, **kwargs)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray import NDArray
    if isinstance(alpha, NDArray):
        return _inv("sample_gamma", alpha, beta, shape=_shape(shape), dtype=dtype)
    return _inv("random_gamma", alpha=alpha, beta=beta, shape=_shape(shape),
                dtype=dtype)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _inv("random_exponential", lam=1.0 / scale, shape=_shape(shape),
                dtype=dtype)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _inv("random_poisson", lam=lam, shape=_shape(shape), dtype=dtype)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _inv("random_negative_binomial", k=k, p=p, shape=_shape(shape),
                dtype=dtype)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None, out=None):
    return _inv("random_generalized_negative_binomial", mu=mu, alpha=alpha,
                shape=_shape(shape), dtype=dtype)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    return _inv("sample_multinomial", data, shape=_shape(shape),
                get_prob=get_prob, dtype=dtype)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    return _inv("random_randint", low=low, high=high, shape=_shape(shape),
                dtype=dtype)


def shuffle(data, **kw):
    return _inv("shuffle", data)
