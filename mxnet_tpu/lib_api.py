"""External operator-library ABI: load out-of-tree ops at runtime.

TPU-native analog of the reference's library-loading surface:

- ``MXLoadLib`` (ref: src/c_api/c_api.cc:96) dlopens a user library and
  calls its exported ``initialize(int version)`` — the one function the
  1.6 plugin contract requires (ref: include/mxnet/lib_api.h
  ``MXLIB_INITIALIZE_STR``). A truthy return means "compatible,
  registered" (the reference's c_api.cc treats a zero return as
  failure).
- ``python/mxnet/library.py load()`` is the user entry point.

Here a plugin is either:

1. **A Python module** (``.py``) — imported in its own namespace; it
   registers jax-traceable ops via :func:`register_op` (optionally with
   a custom VJP), then the loader calls its ``initialize(version)``.
   These ops are first-class: they trace into XLA, differentiate, and
   fuse like built-ins.
2. **A C shared library** (``.so``) — dlopened via ctypes; after
   ``initialize`` succeeds the loader queries an optional registration
   surface (``_opRegSize`` / ``_opRegName`` / ``_opInferShape`` /
   ``_opCompute``, declared in ``src/lib_api.h``) and wraps each kernel
   in ``jax.pure_callback``: on TPU a foreign C kernel is host compute
   by construction, so the callback island is the honest mapping —
   inputs stream back to the host, the kernel runs, the result is fed
   to the device, and XLA treats it as an opaque node. C-plugin ops are
   forward-only (no VJP) unless the library also exports
   ``_opBackward``.

Loaded ops appear in ``mx.nd``, ``mx.sym`` and the operator registry
immediately, so Gluon/Module graphs can use them like any other op.
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

from ._debug import locktrace as _locktrace

__all__ = ["load", "register_op", "loaded_libraries", "VERSION"]

# MXNET_VERSION analog: major*10000 + minor*100 + patch (ref:
# include/mxnet/lib_api.h version passing convention)
VERSION = 10600

_LOADED = {}
# serializes plugin loads: load() is check-then-act on _LOADED and the
# op registry snapshot/rollback is a critical section — two threads
# loading the same plugin concurrently would register its ops twice.
# Reentrant: a plugin's module body may itself load() a dependency
# plugin on the same thread
_LOAD_LOCK = _locktrace.named_lock("lib_api.load", reentrant=True)


def loaded_libraries():
    """Paths of libraries loaded this process (ref: MXLibInfo* family)."""
    return sorted(_LOADED)


def _install_wrappers(names):
    """(Re)install nd/sym wrappers for `names`, overwriting any existing
    entry — unlike additive populate(), a plugin that overrides a
    built-in must actually take effect through mx.nd/mx.sym."""
    import mxnet_tpu.ndarray as _nd
    import mxnet_tpu.symbol as _sym
    from .ndarray.register import make_op_func
    from .symbol.register import make_symbol_op_func
    from .ops import registry as _registry
    for n in names:
        opdef = _registry.get_op(n)
        vars(_nd)[n] = make_op_func(opdef, n)
        vars(_sym)[n] = make_symbol_op_func(opdef, n)


def _registry_snapshot():
    from .ops import registry as _registry
    return dict(_registry._OPS)


def _registry_rollback(snapshot):
    """Restore the registry (and nd/sym wrappers) to `snapshot` — a
    failed initialize must leave nothing behind (MXLoadLib contract:
    zero return means nothing was registered)."""
    import mxnet_tpu.ndarray as _nd
    import mxnet_tpu.symbol as _sym
    from .ops import registry as _registry
    added = set(_registry._OPS) - set(snapshot)
    changed = [n for n in snapshot
               if _registry._OPS.get(n) is not snapshot[n]]
    _registry._OPS.clear()
    _registry._OPS.update(snapshot)
    for n in added:
        vars(_nd).pop(n, None)
        vars(_sym).pop(n, None)
    _install_wrappers(changed)


def register_op(name, forward, backward=None, aliases=(), no_grad=False):
    """Register an out-of-tree operator into the live registry.

    Parameters
    ----------
    name : str
        Op name; becomes ``mx.nd.<name>`` / ``mx.sym.<name>``.
    forward : callable
        Pure function ``fn(*jax_arrays, **static_params) -> array`` —
        jax-traceable (jnp/lax), so it compiles and fuses like any
        built-in op.
    backward : callable, optional
        Custom VJP ``fn(residual_inputs, cotangent) -> tuple(grads)``.
        When given, ``forward`` is wrapped in ``jax.custom_vjp``;
        otherwise jax autodiff of ``forward`` applies (or the op is
        marked non-differentiable with ``no_grad=True``).
    """
    import functools
    import inspect
    import warnings

    import jax
    from .ops import registry as _registry

    fn = forward
    if backward is not None:
        # custom_vjp can't bind keyword args, so build one wrapped fn
        # per distinct static-kwarg binding (cached; kwargs of an op
        # call are hashable static params by the registry contract)
        bwd_params = inspect.signature(backward).parameters
        bwd_takes_kw = (len(bwd_params) > 2 or any(
            p.kind == inspect.Parameter.VAR_KEYWORD
            for p in bwd_params.values()))

        @functools.lru_cache(maxsize=None)
        def _vjp_for(kw_items):
            kw = dict(kw_items)

            @jax.custom_vjp
            def f(*args):
                return forward(*args, **kw)

            def _fwd(*args):
                return forward(*args, **kw), args

            def _bwd(residuals, g):
                if bwd_takes_kw:
                    return tuple(backward(residuals, g, **kw))
                return tuple(backward(residuals, g))

            f.defvjp(_fwd, _bwd)
            return f

        def fn(*args, **kwargs):
            return _vjp_for(tuple(sorted(kwargs.items())))(*args)

        fn.__name__ = name
        fn.__signature__ = inspect.signature(forward)
    existing = _registry._OPS.get(name)
    if existing is not None:
        warnings.warn("external library overrides operator %r" % name,
                      RuntimeWarning, stacklevel=2)
    _registry.register(name, no_grad=no_grad, aliases=aliases)(fn)
    _install_wrappers((name,) + tuple(aliases))
    return fn


def _load_python_plugin(path):
    import importlib.util
    modname = "mxnet_tpu_lib_%s" % os.path.basename(path)[:-3]
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    init = getattr(mod, "initialize", None)
    if init is None:
        raise RuntimeError(
            "plugin %s does not export initialize(version) "
            "(ref: lib_api.h MXLIB_INITIALIZE_STR contract)" % path)
    if not init(VERSION):
        raise RuntimeError("library %s failed to initialize "
                           "(incompatible with version %d)" % (path, VERSION))
    return mod


_MAX_NDIM = 8


def _wrap_c_op(lib, idx, name):
    """Build a jax-callable from a C plugin kernel via pure_callback."""
    import jax
    import jax.numpy as jnp

    infer = lib._opInferShape
    infer.restype = ctypes.c_int
    compute = lib._opCompute
    compute.restype = ctypes.c_int

    def _infer_shape(in_shapes):
        nin = len(in_shapes)
        shape_arrs = [np.asarray(s, dtype=np.int64) for s in in_shapes]
        ptrs = (ctypes.POINTER(ctypes.c_int64) * nin)(
            *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
              for s in shape_arrs])
        ndims = (ctypes.c_int * nin)(*[len(s) for s in in_shapes])
        out_shape = (ctypes.c_int64 * _MAX_NDIM)()
        out_ndim = ctypes.c_int(0)
        rc = infer(idx, nin, ptrs, ndims, out_shape,
                   ctypes.byref(out_ndim))
        if rc != 0:
            raise RuntimeError("%s: _opInferShape failed (%d)" % (name, rc))
        return tuple(out_shape[i] for i in range(out_ndim.value))

    def _host_kernel(out_shape, *arrays):
        # out_shape was inferred once at trace time (op_fn) — no extra
        # ctypes round-trip per callback execution
        arrays = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
        nin = len(arrays)
        out = np.empty(out_shape, dtype=np.float32)
        data_ptrs = (ctypes.POINTER(ctypes.c_float) * nin)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        shape_arrs = [np.asarray(a.shape, dtype=np.int64) for a in arrays]
        shape_ptrs = (ctypes.POINTER(ctypes.c_int64) * nin)(
            *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
              for s in shape_arrs])
        ndims = (ctypes.c_int * nin)(*[a.ndim for a in arrays])
        oshape = np.asarray(out_shape, dtype=np.int64)
        rc = compute(idx, nin, data_ptrs, shape_ptrs, ndims,
                     out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     oshape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                     len(out_shape))
        if rc != 0:
            raise RuntimeError("%s: _opCompute failed (%d)" % (name, rc))
        return out

    def op_fn(*arrays):
        import functools
        arrays = [jnp.asarray(a, dtype=jnp.float32) for a in arrays]
        out_shape = _infer_shape([a.shape for a in arrays])
        result_sd = jax.ShapeDtypeStruct(out_shape, jnp.float32)
        kernel = functools.partial(_host_kernel, out_shape)
        return jax.pure_callback(kernel, result_sd, *arrays,
                                 vmap_method="sequential")

    op_fn.__name__ = name
    op_fn.__doc__ = ("External C-plugin op %r (host-callback kernel; "
                     "forward-only)" % name)
    return op_fn


def _load_c_plugin(path):
    lib = ctypes.CDLL(path)
    init = lib.initialize
    init.restype = ctypes.c_int
    init.argtypes = [ctypes.c_int]
    if not init(VERSION):
        raise RuntimeError("library %s failed to initialize "
                           "(incompatible with version %d)" % (path, VERSION))
    # optional op-registration surface
    if not hasattr(lib, "_opRegSize"):
        return lib
    lib._opRegSize.restype = ctypes.c_int
    lib._opRegName.restype = ctypes.c_char_p
    n = lib._opRegSize()
    for i in range(n):
        name = lib._opRegName(i).decode()
        register_op(name, _wrap_c_op(lib, i, name), no_grad=True)
    return lib


def load(path, verbose=True):
    """Load an external operator library (ref: python/mxnet/library.py
    load(), src/c_api/c_api.cc:96 MXLoadLib).

    ``path`` must be an absolute path to a ``.so`` (C plugin) or ``.py``
    (Python plugin) file. Idempotent per path.
    """
    from .base import MXNetError
    if not os.path.exists(path):
        raise MXNetError("load path %s does NOT exist" % path)
    if not os.path.isabs(path):
        raise MXNetError("load path %s is not an absolute path" % path)
    ext = os.path.splitext(path)[1]
    if ext not in (".so", ".dll", ".py"):
        raise MXNetError("load path %s is NOT a library file" % path)
    with _LOAD_LOCK:
        if path in _LOADED:
            return _LOADED[path]
        snapshot = _registry_snapshot()
        try:
            handle = (_load_python_plugin(path) if ext == ".py"
                      else _load_c_plugin(path))
        except Exception:
            _registry_rollback(snapshot)
            raise
        _LOADED[path] = handle
    if verbose:
        import logging
        logging.getLogger("mxnet_tpu").info("loaded library %s", path)
    return handle
