"""Gluon: the imperative neural-network API.

ref: python/mxnet/gluon/__init__.py.
"""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .trainer import Trainer  # noqa: F401
from .fused_step import FusedTrainStep, train_step  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import utils  # noqa: F401


def __getattr__(name):
    # heavier subpackages loaded lazily (data has worker machinery, rnn has
    # scan kernels, model_zoo has model definitions, contrib has estimator)
    import importlib
    if name in ("data", "rnn", "model_zoo", "contrib"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
