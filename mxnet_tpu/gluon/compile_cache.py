"""Persistent AOT compile cache (ISSUE 19b): replay yesterday's XLA
work from disk instead of recompiling the world.

The fused step already compiles ahead-of-time on every compile step (the
``_record_compile`` seam captures the executable for cost/HLO/memory
attribution). This module adds the durable half: the serialized
executable (``jax.experimental.serialize_executable``) lands under
``MXTPU_COMPILE_CACHE_DIR`` keyed by the FULL compile signature — which
already contains the signature-token registry snapshot, the aval
signature of every operand, the mesh fingerprint and the optimizer
static key — plus the jax/jaxlib versions and backend platform, so a
cache entry can never replay under a different graph-shaping
configuration, library build, or backend than the one that compiled it.

Contract (the "never fatal" rule): every miss, deserialize failure,
version skew, or store error degrades to a fresh trace+compile and
ticks a counter in ``metrics()['compile_cache']`` — the cache can only
ever make a run faster, never wrong and never dead. Entries publish via
temp-write + atomic rename (`base.atomic_write`), so a crashed writer
leaves no torn entry for the next process to trip over.
"""
from __future__ import annotations

import hashlib
import os
import pickle

from .. import base as _base
from .. import profiler as _profiler
from ..base import getenv as _getenv

__all__ = ["enabled", "cache_dir", "cache_path", "load", "store",
           "stats", "reset_stats"]

# mxlint: disable=MX003 (GIL-atomic best-effort counters, same contract as fused_step._STATS)
_STATS = {
    "hits": 0,       # executable served from the persistent cache
    "misses": 0,     # no entry for this key (fresh compile follows)
    "stores": 0,     # executables serialized to disk
    "deserialize_errors": 0,  # entry present but unloadable (version
                              # skew the key missed, torn/corrupt file,
                              # backend drift) — counted, then a fresh
                              # compile; never fatal
    "store_errors": 0,        # serialize/write failed — compile kept,
                              # cache entry lost
}


def stats():
    """Snapshot of the persistent-cache counters."""
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


# surfaces as metrics()['compile_cache'] and a dumps() line
_profiler.register_stats_provider("compile_cache", stats, reset_stats)


def cache_dir():
    """The cache root, or ``None`` when the cache is off. Read per call
    (not pinned at import) so tests and late-configured launchers can
    flip it; the var is also a signature token, so flipping it mid-run
    lands every later compile on a fresh in-memory key too."""
    d = _getenv("MXTPU_COMPILE_CACHE_DIR", "")
    return d or None


def enabled():
    return cache_dir() is not None


def _fingerprint():
    """Environment half of the key: serialized executables are only
    valid for the exact jax/jaxlib build and backend that produced
    them."""
    import jax
    import jaxlib
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    return (jax.__version__, getattr(jaxlib, "__version__", "?"),
            platform)


def cache_path(sig_key):
    """Entry path for one full compile-signature key. The digest is
    sha256 of the key tuple's repr (avals, token snapshots and static
    keys all repr deterministically — the same property the compile
    registry's crc32 keyhash relies on) plus the version/backend
    fingerprint."""
    d = cache_dir()
    if d is None:
        return None
    h = hashlib.sha256(
        repr((sig_key, _fingerprint())).encode("utf-8")).hexdigest()
    return os.path.join(d, h[:32] + ".xc")


def load(sig_key):
    """Return the cached compiled executable for ``sig_key``, or
    ``None`` (miss or unloadable — counted). The caller falls back to
    ``lower().compile()`` either way."""
    path = cache_path(sig_key)
    if path is None:
        return None
    if not os.path.exists(path):
        _STATS["misses"] += 1
        return None
    try:
        from jax.experimental.serialize_executable import \
            deserialize_and_load
        with open(path, "rb") as f:
            blob, in_tree, out_tree = pickle.load(f)
        compiled = deserialize_and_load(blob, in_tree, out_tree)
    except Exception as e:
        _STATS["deserialize_errors"] += 1
        _profiler.record_op(
            "compile_cache.deserialize_error", 0.0, category="elastic",
            lane="user",
            args={"error": "%s: %s" % (type(e).__name__, e)})
        return None
    _STATS["hits"] += 1
    return compiled


def store(sig_key, compiled):
    """Serialize one compiled executable under its signature key.
    Best-effort: a failure loses the cache entry, never the compile.
    Returns True when the entry published."""
    path = cache_path(sig_key)
    if path is None:
        return False
    try:
        from jax.experimental.serialize_executable import serialize
        blob, in_tree, out_tree = serialize(compiled)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with _base.atomic_write(path, "wb") as f:
            pickle.dump((blob, in_tree, out_tree), f,
                        protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        _STATS["store_errors"] += 1
        return False
    _STATS["stores"] += 1
    return True
