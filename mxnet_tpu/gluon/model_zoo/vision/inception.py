"""Inception V3 (Szegedy et al., arXiv:1512.00567).

ref: python/mxnet/gluon/model_zoo/vision/inception.py (names + spec only).
Parallel towers concatenate on the channel axis; XLA fuses the BN/ReLU
chains into the conv epilogues.
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for setting in conv_settings:
        kwargs = {}
        channels, kernel_size, strides, padding = setting
        kwargs["channels"] = channels
        kwargs["kernel_size"] = kernel_size
        if strides is not None:
            kwargs["strides"] = strides
        if padding is not None:
            kwargs["padding"] = padding
        out.add(_make_basic_conv(**kwargs))
    return out


class _Concurrent(HybridBlock):
    """Run child branches on the same input, concat outputs on channels."""

    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        self.branches = branches
        for i, b in enumerate(branches):
            self.register_child(b, "branch%d" % i)
            self._params.update(b.collect_params())

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self.branches], dim=1)


def _make_A(pool_features, prefix):
    return _Concurrent([
        _make_branch(None, (64, 1, None, None)),
        _make_branch(None, (48, 1, None, None), (64, 5, None, 2)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                     (96, 3, None, 1)),
        _make_branch("avg", (pool_features, 1, None, None)),
    ], prefix=prefix)


def _make_B(prefix):
    return _Concurrent([
        _make_branch(None, (384, 3, 2, None)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                     (96, 3, 2, None)),
        _make_branch("max"),
    ], prefix=prefix)


def _make_C(channels_7x7, prefix):
    return _Concurrent([
        _make_branch(None, (192, 1, None, None)),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0))),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (192, (1, 7), None, (0, 3))),
        _make_branch("avg", (192, 1, None, None)),
    ], prefix=prefix)


def _make_D(prefix):
    return _Concurrent([
        _make_branch(None, (192, 1, None, None), (320, 3, 2, None)),
        _make_branch(None, (192, 1, None, None), (192, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0)), (192, 3, 2, None)),
        _make_branch("max"),
    ], prefix=prefix)


class _SplitConcat(HybridBlock):
    """E-block inner split: one stem feeding two convs, concat outputs."""

    def __init__(self, stem, paths, **kwargs):
        super().__init__(**kwargs)
        self.stem = stem
        self.paths = paths
        if stem is not None:
            self.register_child(stem, "stem")
            self._params.update(stem.collect_params())
        for i, p in enumerate(paths):
            self.register_child(p, "path%d" % i)
            self._params.update(p.collect_params())

    def hybrid_forward(self, F, x):
        if self.stem is not None:
            x = self.stem(x)
        return F.concat(*[p(x) for p in self.paths], dim=1)


def _make_E(prefix):
    return _Concurrent([
        _make_branch(None, (320, 1, None, None)),
        _SplitConcat(_make_basic_conv(channels=384, kernel_size=1),
                     [_make_basic_conv(channels=384, kernel_size=(1, 3),
                                       padding=(0, 1)),
                      _make_basic_conv(channels=384, kernel_size=(3, 1),
                                       padding=(1, 0))]),
        _SplitConcat(
            _make_branch(None, (448, 1, None, None), (384, 3, None, 1)),
            [_make_basic_conv(channels=384, kernel_size=(1, 3),
                              padding=(0, 1)),
             _make_basic_conv(channels=384, kernel_size=(3, 1),
                              padding=(1, 0))]),
        _make_branch("avg", (192, 1, None, None)),
    ], prefix=prefix)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                               strides=2))
            self.features.add(_make_basic_conv(channels=32, kernel_size=3))
            self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                               padding=1))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_basic_conv(channels=80, kernel_size=1))
            self.features.add(_make_basic_conv(channels=192, kernel_size=3))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_A(32, "A1_"))
            self.features.add(_make_A(64, "A2_"))
            self.features.add(_make_A(64, "A3_"))
            self.features.add(_make_B("B_"))
            self.features.add(_make_C(128, "C1_"))
            self.features.add(_make_C(160, "C2_"))
            self.features.add(_make_C(160, "C3_"))
            self.features.add(_make_C(192, "C4_"))
            self.features.add(_make_D("D_"))
            self.features.add(_make_E("E1_"))
            self.features.add(_make_E("E2_"))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def inception_v3(pretrained=False, ctx=None, root=None, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline")
    return Inception3(**kwargs)
