"""ResNet V1/V2 for the model zoo.

ref: python/mxnet/gluon/model_zoo/vision/resnet.py (architecture + get_model
names only; implementation is this framework's Gluon idiom on jax/XLA).

V1 is the post-activation variant (He et al. 2015, arXiv:1512.03385); V2 is
pre-activation (arXiv:1603.05027). All convs lower to
`lax.conv_general_dilated`, which XLA tiles onto the MXU; cast the net to
bfloat16 (`net.cast('bfloat16')`) for the fast TPU path.
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _bn_axis(layout):
    return -1 if layout == "NHWC" else 1


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels, layout=layout)


# -- fused BN->ReLU->conv3x3 path (fuse=True, NHWC only) --------------------
# XLA:TPU does not fuse elementwise producers into convolutions
# (benchmark/fusion_probe.py: 2.6x operand bytes), so the normalized
# activation between a BatchNorm and the following 3x3 conv is a full HBM
# round-trip on the XLA path. These private OpDefs route that link through
# the Pallas kernel in pallas_kernels/conv_fused.py instead: the BN fold
# (s = gamma*rsqrt(var+eps), b = beta - mean*s) is one tape op whose stat
# math matches ops.nn.batch_norm exactly, and the conv consumes the RAW
# previous conv output with scale/bias/ReLU applied in VMEM. Kept out of
# the global op registry: opperf/op-parity sweeps synthesize inputs by
# shape heuristics these composite signatures don't fit.
_BN_FOLD_OP = None
_FUSED_CONV_OP = None


def _fused_opdefs():
    global _BN_FOLD_OP, _FUSED_CONV_OP
    if _BN_FOLD_OP is None:
        import jax
        import jax.numpy as jnp
        from ....ops.registry import OpDef
        from ....ops.nn import batch_moments

        def _bn_fold(y, gamma, beta, eps=1e-5):
            # the SAME stat computation as ops.nn.batch_norm — shared
            # helper so the exact-running-stats contract can't drift
            mean, var = batch_moments(y, (0, 1, 2), axis=3)
            s = gamma.astype(jnp.float32) * jax.lax.rsqrt(
                var.astype(jnp.float32) + eps)
            b = beta.astype(jnp.float32) - mean.astype(jnp.float32) * s
            return s, b, mean, var

        from ....ops.nn import _ckpt_name

        def _fused_conv(x, s, b, w, relu=True):
            from ....pallas_kernels.conv_fused import \
                fused_scale_relu_conv3x3
            w_hwio = jnp.transpose(w, (2, 3, 1, 0))   # OIHW -> HWIO
            # tagged like every XLA-path conv so conv_outs remat
            # policies keep it instead of re-running the Pallas kernel
            return _ckpt_name(
                fused_scale_relu_conv3x3(x, s, b, w_hwio, relu=relu),
                "conv_out")

        _BN_FOLD_OP = OpDef("_fused_bn_fold", _bn_fold)
        _FUSED_CONV_OP = OpDef("_fused_scale_relu_conv3x3", _fused_conv)
    return _BN_FOLD_OP, _FUSED_CONV_OP


def _fused_producer_conv(bn, conv, y, F):
    """y -> conv3x3(relu(bn(y))) with the normalize/ReLU chain fused into
    the conv's VMEM operand load; replicates the BatchNorm block's
    running-stat updates (gluon/nn/basic_layers.py BatchNorm)."""
    from .... import autograd
    from ...block import report_aux_update
    from ....ndarray.register import invoke

    fold_op, conv_op = _fused_opdefs()
    if bn.gamma._data is None:
        bn._infer_param_shapes(y)
    gamma, beta = bn.gamma.data(), bn.beta.data()
    if not bn._scale:
        # batch_norm's fix_gamma (=not scale) replaces gamma with ones
        # at dispatch; the fused fold below uses gamma VERBATIM, so a
        # scale=False BN would silently train gamma. All model-zoo
        # blocks use scale=True; substitute ones to keep the semantics
        # identical if the helper is ever reused with scale=False.
        gamma = F.ones_like(gamma)
    if autograd.is_training() and not bn._use_global_stats:
        s, b, mean, var = invoke(fold_op, (y, gamma, beta),
                                 {"eps": bn._eps})
        m = bn._momentum
        report_aux_update(
            bn.running_mean,
            m * bn.running_mean.data()._data + (1 - m) * mean._data)
        report_aux_update(
            bn.running_var,
            m * bn.running_var.data()._data + (1 - m) * var._data)
    else:
        rm = F.cast(bn.running_mean.data(), "float32")
        rv = F.cast(bn.running_var.data(), "float32")
        s = F.cast(gamma, "float32") * F.rsqrt(rv + bn._eps)
        b = F.cast(beta, "float32") - rm * s
    return invoke(conv_op, (y, s, b, conv.weight.data()), {"relu": True})


def _is_nd(F):
    return getattr(F, "__name__", "").endswith("ndarray")


class BasicBlockV1(HybridBlock):
    """Two 3x3 convs, post-activation residual unit. ``fuse=True`` routes
    the BN->ReLU->second-conv link through the Pallas fused kernel."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", fuse=False, **kwargs):
        super().__init__(**kwargs)
        self._fuse = fuse
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        if self._fuse and _is_nd(F):
            # body: conv3x3(stride), bn, relu, conv3x3(1), bn — fuse the
            # bn+relu producer into the second conv's operand load
            y = self.body[0](x)
            y = _fused_producer_conv(self.body[1], self.body[3], y, F)
            x = self.body[4](y)
        else:
            x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    """1x1 -> 3x3 -> 1x1 bottleneck, post-activation. ``fuse=True``
    routes the BN->ReLU->3x3 link through the Pallas fused kernel
    (pallas_kernels/conv_fused.py) so the normalized activation never
    round-trips HBM; all 3x3 convs in this block are stride 1, which is
    exactly the kernel's domain."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", fuse=False, **kwargs):
        super().__init__(**kwargs)
        self._fuse = fuse
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                use_bias=False, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                use_bias=False, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        if self._fuse and _is_nd(F):
            y = self.body[0](x)                       # 1x1 (stride)
            y = _fused_producer_conv(self.body[1], self.body[3], y, F)
            for i in (4, 5, 6, 7):                     # bn, relu, 1x1, bn
                y = self.body[i](y)
            x = y
        else:
            x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    """Pre-activation two-conv residual unit."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """Pre-activation bottleneck residual unit."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = nn.BatchNorm(axis=ax)
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """`layout="NHWC"` builds the TPU-native channels-last variant: the
    public API still takes NCHW batches (one boundary transpose), and
    weights stay OIHW so checkpoints are layout-independent — but every
    conv/BN/pool runs channels-last, the layout XLA:TPU tiles onto the
    MXU without relayout copies."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", fuse=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        if fuse and layout != "NHWC":
            raise ValueError("fuse=True requires layout='NHWC' (the Pallas "
                             "fused conv kernel is channels-last)")
        self._layout = layout
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False, layout=layout))
                self.features.add(nn.BatchNorm(axis=ax))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i], layout=layout, fuse=fuse))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW", fuse=False):
        # fuse="auto": apply the Pallas fused kernel only where it beats
        # XLA's native conv — small feature maps / deep channels (the
        # im2col VMEM tax loses on large maps; see conv_fused.py). The
        # 3x3 width is channels//4 in bottlenecks, channels in basics.
        width3x3 = channels // 4 if block in (BottleneckV1, BottleneckV2) \
            else channels
        block_fuse = bool(fuse) if fuse != "auto" else width3x3 >= 512
        layer = nn.HybridSequential(prefix="stage%d_" % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout,
                            fuse=block_fuse, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, fuse=block_fuse, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        if self._layout == "NHWC":
            x = F.transpose(x, axes=(0, 2, 3, 1))
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    """Pre-activation ResNet; see ResNetV1 for `layout="NHWC"`."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False,
                                           axis=ax))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False, layout=layout))
                self.features.add(nn.BatchNorm(axis=ax))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels, layout=layout))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW"):
        layer = nn.HybridSequential(prefix="stage%d_" % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout,
                            prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        if self._layout == "NHWC":
            x = F.transpose(x, axes=(0, 2, 3, 1))
        x = self.features(x)
        return self.output(x)


# spec table: num_layers -> (block type tag, per-stage depths, channels)
resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """Build a ResNet. `pretrained` weights are unavailable offline (raises)."""
    if pretrained:
        raise RuntimeError("pretrained weights are not available in this "
                           "offline build; initialize() and train instead")
    assert num_layers in resnet_spec, \
        "invalid resnet depth %d; options: %s" % (num_layers,
                                                  sorted(resnet_spec))
    assert version in (1, 2)
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    return resnet_class(block_class, layers, channels, **kwargs)


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
