"""Model zoo: pre-defined network architectures.

TPU-native re-design of the reference model zoo
(ref: python/mxnet/gluon/model_zoo/__init__.py). Pretrained-weight download
is stubbed out (zero-egress environment); architectures, parameter shapes and
`get_model` names match the reference so checkpoints written by
`save_parameters` round-trip.
"""
from . import vision
from .vision import get_model

__all__ = ["vision", "get_model"]
