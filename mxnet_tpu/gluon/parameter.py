"""Parameter and ParameterDict.

TPU-native re-design of Gluon parameters
(ref: python/mxnet/gluon/parameter.py:47 Parameter, :507 Constant,
:705 ParameterDict). Deferred initialization (shape inferred at first
forward) is kept; multi-device replication is replaced by mesh sharding —
a Parameter holds ONE logical NDArray whose placement/sharding is governed
by the active mesh (see mxnet_tpu/parallel), not per-GPU copies.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import canonical_dtype
from ..context import cpu, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import autograd
from .. import storage as _storage

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(Exception):
    """ref: python/mxnet/gluon/parameter.py:39."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = canonical_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._stype = stype
        self._grad_stype = grad_stype  # row_sparse -> Trainer ships rows
        self._data = None          # NDArray
        self._grad = None
        self._deferred_init = None  # (init, ctx, default_init)
        self._sharding = None      # parallel placement hint (PartitionSpec-like)
        self._trainer = None

    def _set_trainer(self, trainer):
        """ref: parameter.py _set_trainer — row_sparse params are bound to
        one trainer (they pull rows through it); dense params may move."""
        if self._stype != "default" and self._trainer is not None and \
                trainer is not None and self._trainer is not trainer:
            raise RuntimeError(
                "Failed to set the trainer for Parameter '%s' because it "
                "was already set. More than one trainers for a %s Parameter "
                "is not supported." % (self.name, self._stype))
        self._trainer = trainer

    # -- core -------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None and req != "null":
            self._init_grad()

    def _shape_known(self):
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """ref: parameter.py Parameter.initialize."""
        from .. import initializer as _initializer
        if self._data is not None and not force_reinit:
            return
        default_init = default_init or _initializer.Uniform()
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape %s and deferred init is not allowed." % (self.name,
                                                                self.shape))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        from .. import initializer as _initializer
        specific = init if init is not None else self.init
        initializer = specific if specific is not None else default_init
        if isinstance(initializer, str):
            initializer = _initializer.get(initializer)
        data = _np.zeros(self.shape, self.dtype)
        if specific is not None:
            # a parameter-specific initializer bypasses the name-suffix
            # dispatch (ref: initializer.py:142 — the __init__ attr path
            # calls _init_weight directly)
            if hasattr(initializer, "_init_weight"):
                initializer._init_weight(self.name, data)
            else:
                initializer(self.name, data)   # Mixed / callables
        else:
            initializer._init_weight_dispatch(self.name, data)
        ctx = ctx if ctx is not None and not isinstance(ctx, (list, tuple)) \
            else (ctx[0] if ctx else current_context())
        self._data = nd.array(data, ctx=ctx, dtype=self.dtype)
        # allocation-ledger tag upgrade: nd.array registered the buffer
        # as generic 'other'; adopting it into a Parameter makes it
        # 'param' (the specific tag wins the ledger slot)
        _storage.ledger_register(self._data, "param", site=self.name)
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self, shape):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                "Parameter '%s' has not been initialized" % self.name)
        self.shape = tuple(shape)
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        self._data.attach_grad(self._grad_req)
        self._grad = self._data._grad

    # -- access -----------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter '%s' deferred; run a forward pass or set "
                    "shape first" % self.name)
            raise RuntimeError(
                "Parameter '%s' has not been initialized. Call initialize()"
                % self.name)
        return self._data

    def list_data(self):
        return [self._data]

    def grad(self, ctx=None):
        if self._data is None or self._data._grad is None:
            raise RuntimeError("Parameter '%s' has no gradient (grad_req=%s)"
                               % (self.name, self._grad_req))
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._data.context] if self._data is not None else []

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            self._data._grad._data = jnp.zeros_like(self._data._grad._data)

    def set_data(self, data):
        data = data if isinstance(data, NDArray) else nd.array(data)
        known = self.shape is not None and all(
            d not in (0, None, -1) for d in self.shape)
        if known and tuple(data.shape) != tuple(self.shape):
            # ref: parameter.py Parameter._load_init shape assert — a
            # checkpoint/assignment mismatch must not pass silently
            raise ValueError(
                "Parameter %r: cannot set data of shape %s on declared "
                "shape %s" % (self.name, tuple(data.shape),
                              tuple(self.shape)))
        if self._data is None:
            self.shape = data.shape
            self._data = data
            self._deferred_init = None
            if self._grad_req != "null":
                self._init_grad()
        else:
            self._data._data = data._data.astype(self._data.dtype)
        _storage.ledger_register(self._data, "param", site=self.name)

    def _adopt_fused(self, weight_data, grad_data=None):
        """Adopt one fused-train-step result into this parameter's live
        buffers: the updated weight into ``data()`` (dtype preserved)
        and, when given, the raw gradient the program computed into
        ``grad()`` — then age the grad flag, because the same program
        already consumed it (mirrors Trainer._update's bookkeeping, so
        eager and fused steps leave identical state behind)."""
        data = self.data()
        data._data = weight_data if weight_data.dtype == data.dtype \
            else weight_data.astype(data.dtype)
        # allocation-ledger choke point (ISSUE 13a): the fused step's
        # donated program produced fresh weight/grad buffers — register
        # them; the buffers they replaced retire via weakref death (CPU)
        # or is_deleted() (donation), observed by the next drain
        _storage.ledger_register(data, "param", site=self.name)
        if grad_data is not None:
            autograd.deliver_grad(data, grad_data)
            if data._grad is not None:
                _storage.ledger_register(data._grad, "grad",
                                          site=self.name)
        data._fresh_grad = False

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            if self._grad_req != "null":
                self._init_grad()

    def cast(self, dtype):
        self.dtype = canonical_dtype(dtype)
        if self._data is not None:
            self._data._data = self._data._data.astype(self.dtype)
            if self._grad_req != "null":
                self._init_grad()

    def var(self):
        from ..symbol import Symbol
        return Symbol.var(self.name, shape=self.shape)

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape,
                                                      _np.dtype(self.dtype).name)


class Constant(Parameter):
    """Non-learnable parameter (ref: parameter.py:507)."""

    def __init__(self, name, value):
        value = value if isinstance(value, _np.ndarray) else \
            (value.asnumpy() if isinstance(value, NDArray) else _np.asarray(value))
        self.value = value

        from .. import initializer as _initializer

        class _CInit(_initializer.Initializer):
            def _init_weight(self, _, arr):
                arr[:] = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit())


class ParameterDict:
    """ref: python/mxnet/gluon/parameter.py:705."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name):
        return self._params[name]

    def get(self, name, **kwargs):
        """Create-or-retrieve with the dict's prefix."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            # update unknown shapes with now-known values
            if kwargs.get("shape") is not None:
                shape = kwargs["shape"]
                shape = (shape,) if isinstance(shape, int) else tuple(shape)
                if param.shape is None or not param._shape_known():
                    param.shape = shape
        return param

    def get_constant(self, name, value=None):
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise KeyError("constant %r not found and no value given" % name)
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full):
        if full in self._params:
            return self._params[full]
        if self._shared is not None and full in self._shared:
            self._params[full] = self._shared[full]
            return self._params[full]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("duplicate parameter name %r" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        arg = {}
        for name, p in self._params.items():
            if p._data is None:
                continue
            k = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            arg[k] = p.data()
        nd.save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError("Parameter %r missing in file %s" % (name,
                                                                    filename))
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise KeyError("File %s contains extra parameters: %s"
                               % (filename, sorted(extra)))

    def __repr__(self):
        return "ParameterDict(%s)" % ", ".join(self._params)
