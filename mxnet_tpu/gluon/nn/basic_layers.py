"""Core Gluon layers (ref: python/mxnet/gluon/nn/basic_layers.py).

Each layer's ``hybrid_forward`` is built from registry ops, so the hybridized
whole-model trace fuses into one XLA computation on TPU.
"""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ..block import Block, HybridBlock, report_aux_update
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU"]


class Sequential(Block):
    """ref: basic_layers.py Sequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            name = str(len(self._children))
            self.register_child(block, name)
            self._params.update(block.collect_params())

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __call__(self, *args):
        return self.forward(*args)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        return list(self._children.values())[idx]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """ref: basic_layers.py HybridSequential — hybridizes to ONE XLA graph."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            name = str(len(self._children))
            self.register_child(block, name)
            self._params.update(block.collect_params())

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        return list(self._children.values())[idx]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (ref: basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        self.act = Activation(activation) if activation else None
        self.weight = self.params.get(
            "weight", shape=(units, in_units), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get(
                "bias", shape=(units,), dtype=dtype, init=bias_initializer,
                allow_deferred_init=True)
        else:
            self.bias = None

    def _shape_hint(self, x, *args):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        hints = {self.weight: (self._units, in_units)}
        if self.bias is not None:
            hints[self.bias] = (self._units,)
        return hints

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return "Dense(%s -> %d)" % (self.weight.shape[1] if
                                    self.weight.shape else "?", self._units)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """ref: basic_layers.py BatchNorm. Running stats are aux params updated
    through report_aux_update so the hybridized trace stays pure."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get(
            "gamma", shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True,
            differentiable=scale)
        self.beta = self.params.get(
            "beta", shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True, differentiable=center)
        self.running_mean = self.params.get(
            "running_mean", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def _shape_hint(self, x, *args):
        c = x.shape[self._axis]
        return {self.gamma: (c,), self.beta: (c,),
                self.running_mean: (c,), self.running_var: (c,)}

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd
        bn = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._eps,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        if len(bn) == 1:
            return bn  # symbolic trace: single visible output
        out, mean, var = bn
        if autograd.is_training() and not self._use_global_stats:
            m = self._momentum
            new_mean = m * running_mean._data + (1 - m) * mean._data \
                if hasattr(mean, "_data") else None
            if new_mean is not None:
                report_aux_update(self.running_mean, new_mean)
                report_aux_update(
                    self.running_var,
                    m * running_var._data + (1 - m) * var._data)
        return out


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True,
                                     differentiable=scale)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True,
                                    differentiable=center)

    def _shape_hint(self, x, *args):
        return {self.gamma: (x.shape[1],), self.beta: (x.shape[1],)}

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True,
                                     differentiable=scale)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True,
                                    differentiable=center)

    def _shape_hint(self, x, *args):
        c = x.shape[self._axis]
        return {self.gamma: (c,), self.beta: (c,)}

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def _shape_hint(self, x, *args):
        return {self.gamma: (x.shape[1],), self.beta: (x.shape[1],)}

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._eps)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func = getattr(nd, function)
        else:
            self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._fname = function
            self._func = None
        else:
            self._func = function
            self._fname = None

    def hybrid_forward(self, F, *args):
        fn = getattr(F, self._fname) if self._fname else self._func
        return fn(*args)


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        # _act_type must exist before Block.__init__ calls _alias()
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return str(self._act_type)

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer
        self.alpha = self.params.get(
            "alpha", shape=(1,),
            init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
