"""Gluon recurrent API (ref: python/mxnet/gluon/rnn/__init__.py)."""
from .rnn_cell import *  # noqa: F401,F403
from .rnn_layer import *  # noqa: F401,F403
from .rnn_cell import __all__ as _cell_all
from .rnn_layer import __all__ as _layer_all

__all__ = list(_cell_all) + list(_layer_all)
