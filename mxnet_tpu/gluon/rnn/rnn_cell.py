"""Recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py).

TPU-native re-design: each cell's step is a HybridBlock built from registry
ops, so an ``unroll`` (or an enclosing hybridized model) traces the whole
sequence into ONE XLA program — the per-step engine dispatch of the
reference disappears. For long sequences prefer the fused layers in
``rnn_layer.py`` (lax.scan → one XLA while loop, O(1) trace size).

Gate semantics match the reference exactly: LSTM [i, f, g, o]
(rnn_cell.py:428), GRU [r, z, n] with n = tanh(i2h_n + r * h2h_n)
(rnn_cell.py:554).
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to (list_of_t | merged tensor, axis, batch_size)
    (ref: rnn_cell.py _format_sequence)."""
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        length = length or len(inputs)
        batch_size = inputs[0].shape[batch_axis]
        if merge:
            data = nd.stack(*inputs, axis=axis)
            return data, axis, batch_size
        return list(inputs), axis, batch_size
    batch_size = inputs.shape[batch_axis]
    if merge is False:
        seq = [nd.squeeze(x, axis=axis)
               for x in nd.split(inputs, num_outputs=inputs.shape[axis],
                                 axis=axis, squeeze_axis=False)]
        return seq, axis, batch_size
    return inputs, axis, batch_size


def _mask_sequence_variable_length(data, length, valid_length, time_axis,
                                   merged):
    if merged:
        return nd.SequenceMask(data, sequence_length=valid_length,
                               use_sequence_length=True, axis=time_axis)
    outs = nd.SequenceMask(nd.stack(*data, axis=0),
                           sequence_length=valid_length,
                           use_sequence_length=True, axis=0)
    return [nd.squeeze(x, axis=0) for x in
            nd.split(outs, num_outputs=len(data), axis=0,
                     squeeze_axis=False)]


class RecurrentCell(Block):
    """Abstract base for RNN cells (ref: rnn_cell.py:125)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset the step counter (ref: rnn_cell.py reset)."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (ref: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **info, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over `length` timesteps (ref: rnn_cell.py:252
        unroll). The python loop disappears into one XLA program when the
        enclosing computation is traced."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = begin_state or self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*ele_list, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(
                outputs, length, valid_length, axis, False)
        if merge_outputs is None:
            merge_outputs = False
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        """ref: rnn_cell.py _get_activation."""
        func = {"tanh": F.tanh, "relu": F.relu,
                "sigmoid": F.sigmoid, "softsign": F.softsign}.get(activation)
        if func is not None:
            return func(inputs)
        if activation == "leaky":
            # ref: conv GRU cells default; LeakyReLU op, slope 0.01
            return F.LeakyReLU(inputs, **kwargs)
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs)

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell whose step is hybrid-traceable (ref: rnn_cell.py:318)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, x, *args):
        return HybridBlock.forward(self, x, *args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_i2h x + b_i2h + W_h2h h + b_h2h)
    (ref: rnn_cell.py:327)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _shape_hint(self, x, *args):
        return {self.i2h_weight: (self._hidden_size, x.shape[-1]),
                self.h2h_weight: (self._hidden_size, self._hidden_size),
                self.i2h_bias: (self._hidden_size,),
                self.h2h_bias: (self._hidden_size,)}

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gates [i, f, g, o] (ref: rnn_cell.py:428)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _shape_hint(self, x, *args):
        h = self._hidden_size
        return {self.i2h_weight: (4 * h, x.shape[-1]),
                self.h2h_weight: (4 * h, h),
                self.i2h_bias: (4 * h,), self.h2h_bias: (4 * h,)}

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=4 * h)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * h)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=-1)
        in_gate = self._get_activation(F, slices[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slices[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slices[2], self._activation)
        out_gate = self._get_activation(F, slices[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, gates [r, z, n] (ref: rnn_cell.py:554)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _shape_hint(self, x, *args):
        h = self._hidden_size
        return {self.i2h_weight: (3 * h, x.shape[-1]),
                self.h2h_weight: (3 * h, h),
                self.i2h_bias: (3 * h,), self.h2h_bias: (3 * h,)}

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h = self._hidden_size
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=3 * h)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias, num_hidden=3 * h)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=-1)
        reset_gate = self._get_activation(F, i2h_r + h2h_r,
                                          self._recurrent_activation)
        update_gate = self._get_activation(F, i2h_z + h2h_z,
                                           self._recurrent_activation)
        next_h_tmp = self._get_activation(F, i2h_n + reset_gate * h2h_n,
                                          self._activation)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class _SequentialCellMixin:
    """Shared stack behavior for the two sequential cell flavors."""

    def add(self, cell):
        self.register_child(cell)
        self._params.update(cell.collect_params())

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class SequentialRNNCell(_SequentialCellMixin, RecurrentCell):
    """Stack of cells applied in sequence each step (ref: rnn_cell.py:682)."""

    def forward(self, *args):
        raise NotImplementedError


class HybridSequentialRNNCell(_SequentialCellMixin, HybridRecurrentCell):
    """Hybrid stack of cells (ref: rnn_cell.py:760)."""


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input each step (ref: rnn_cell.py:835)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that wrap another cell (ref: rnn_cell.py:890)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell
        self.register_child(base_cell, "base_cell")

    @property
    def params(self):
        return self.base_cell.params

    def collect_params(self, select=None):
        return self.base_cell.collect_params(select)

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func or nd.zeros, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py:932; Krueger et al. 2016)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Apply ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds residual connection around the base cell (ref:
    rnn_cell.py:977)."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        if isinstance(outputs, (list, tuple)):
            inputs_l, _, _ = _format_sequence(length, inputs, layout, False)
            outputs = [o + i for o, i in zip(outputs, inputs_l)]
        else:
            merged, _, _ = _format_sequence(length, inputs, layout, True)
            outputs = outputs + merged
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Runs l_cell forward and r_cell backward over the sequence and
    concatenates (ref: rnn_cell.py:1018). Only usable via unroll."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.l_cell = l_cell
        self.r_cell = r_cell
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix
        self._params.update(l_cell.collect_params())
        self._params.update(r_cell.collect_params())

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info([self.l_cell, self.r_cell], batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state([self.l_cell, self.r_cell], **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        if valid_length is None:
            reversed_inputs = list(reversed(inputs))
        else:
            # reverse each sample only within its valid prefix so the
            # backward cell sees real tokens first, not padding
            # (ref: rnn_cell.py:1068 SequenceReverse by valid_length)
            rev = nd.SequenceReverse(nd.stack(*inputs, axis=0), valid_length,
                                     use_sequence_length=True)
            reversed_inputs = [nd.squeeze(x, axis=0) for x in
                               nd.split(rev, num_outputs=length, axis=0,
                                        squeeze_axis=False)]
        begin_state = begin_state or self.begin_state(batch_size=batch_size)

        n_l = len(self.l_cell.state_info(batch_size))
        l_outputs, l_states = self.l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = self.r_cell.unroll(
            length, inputs=reversed_inputs, begin_state=begin_state[n_l:],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_outputs = list(reversed(r_outputs))
        else:
            # un-reverse within the valid prefix (padding outputs stay put,
            # already masked to zero by the inner unroll)
            rev = nd.SequenceReverse(nd.stack(*r_outputs, axis=0),
                                     valid_length, use_sequence_length=True)
            r_outputs = [nd.squeeze(x, axis=0) for x in
                         nd.split(rev, num_outputs=length, axis=0,
                                  squeeze_axis=False)]
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
