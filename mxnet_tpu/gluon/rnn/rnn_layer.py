"""Fused recurrent layers (ref: python/mxnet/gluon/rnn/rnn_layer.py).

TPU-native re-design of the reference's cuDNN-backed fused RNN layers
(ref: src/operator/rnn-inl.h, rnn.cc): parameters are registered unfused
per layer/direction with the reference's names (``l0_i2h_weight`` …) so
checkpoints round-trip, then packed into the single 1-D vector the fused
``RNN`` op consumes.  The op runs each layer's input projection as one
large MXU matmul over the whole sequence and carries only the recurrent
state through a ``lax.scan`` (one XLA while loop — no per-step dispatch,
unlike the reference's per-timestep engine pushes).
"""
from __future__ import annotations

from ... import autograd
from ... import ndarray as nd
from ..block import HybridBlock
from .rnn_cell import RNNCell, LSTMCell, GRUCell, HybridSequentialRNNCell

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


class _RNNLayer(HybridBlock):
    """Base for fused RNN layers (ref: rnn_layer.py:33 _RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None,
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise ValueError(
                "Invalid layout %r; must be one of ['TNC', 'NTC']" % layout)
        if projection_size:
            raise NotImplementedError("LSTMP projection is not supported")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        self._gates = _GATES[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(
                    "{}{}_i2h_weight".format(j, i), (ng * nh, ni),
                    i2h_weight_initializer, dtype)
                self._register_param(
                    "{}{}_h2h_weight".format(j, i), (ng * nh, nh),
                    h2h_weight_initializer, dtype)
                self._register_param(
                    "{}{}_i2h_bias".format(j, i), (ng * nh,),
                    i2h_bias_initializer, dtype)
                self._register_param(
                    "{}{}_h2h_bias".format(j, i), (ng * nh,),
                    h2h_bias_initializer, dtype)
            ni = nh * self._dir

    def _register_param(self, name, shape, init, dtype):
        p = self.params.get(name, shape=shape, init=init, dtype=dtype,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape and shape[1] else None,
            shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _shape_hint(self, x, *args):
        in_size = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        hints = {}
        ng, nh = self._gates, self._hidden_size
        ni = in_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                hints[getattr(self, "{}{}_i2h_weight".format(j, i))] = \
                    (ng * nh, ni)
                hints[getattr(self, "{}{}_h2h_weight".format(j, i))] = \
                    (ng * nh, nh)
                hints[getattr(self, "{}{}_i2h_bias".format(j, i))] = \
                    (ng * nh,)
                hints[getattr(self, "{}{}_h2h_bias".format(j, i))] = \
                    (ng * nh,)
            ni = nh * self._dir
        return hints

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        """Initial recurrent states (ref: rnn_layer.py:159 begin_state)."""
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(name="%sh0_%d" % (self.prefix, i), **info))
        return states

    def unfuse(self):
        """Equivalent stack of unfused cells (ref: rnn_layer.py:116)."""
        get_cell = {
            "rnn_relu": lambda **kw: RNNCell(self._hidden_size,
                                             activation="relu", **kw),
            "rnn_tanh": lambda **kw: RNNCell(self._hidden_size,
                                             activation="tanh", **kw),
            "lstm": lambda **kw: LSTMCell(self._hidden_size, **kw),
            "gru": lambda **kw: GRUCell(self._hidden_size, **kw),
        }[self._mode]
        stack = HybridSequentialRNNCell(prefix=self.prefix, params=self.params)
        with stack.name_scope():
            ni = self._input_size
            for i in range(self._num_layers):
                if self._dir == 2:
                    raise NotImplementedError(
                        "unfuse does not support bidirectional layers")
                stack.add(get_cell(prefix="l%d_" % i, input_size=ni))
                if self._dropout > 0 and i != self._num_layers - 1:
                    from .rnn_cell import DropoutCell
                    stack.add(DropoutCell(self._dropout))
                ni = self._hidden_size
        return stack

    def forward(self, inputs, states=None):
        """Run the fused RNN (ref: rnn_layer.py:234 __call__/forward).

        If ``states`` is None a zero initial state is used and only the
        output sequence is returned; otherwise ``(output, new_states)``.
        """
        skip_states = states is None
        batch_axis = self._layout.find("N")
        batch_size = inputs.shape[batch_axis]
        self._infer_param_shapes(inputs)
        if skip_states:
            states = self.begin_state(batch_size, dtype=inputs.dtype)
        if isinstance(states, nd.NDArray):
            states = [states]
        for st, info in zip(states, self.state_info(batch_size)):
            if list(st.shape) != list(info["shape"]):
                raise ValueError(
                    "Invalid recurrent state shape. Expecting %s, got %s." % (
                        str(info["shape"]), str(st.shape)))
        out = self._forward_kernel(inputs, states)
        return out[0] if skip_states else out

    def _pack_params(self):
        """Flatten per-layer params into the fused op's 1-D vector: all
        weights layer-major (direction inner), then all biases
        (ref: rnn-inl.h GetRnnParamSize packing)."""
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(getattr(self, "%s%d_i2h_weight" % (j, i))
                          .data().reshape(-1))
                ws.append(getattr(self, "%s%d_h2h_weight" % (j, i))
                          .data().reshape(-1))
                bs.append(getattr(self, "%s%d_i2h_bias" % (j, i))
                          .data().reshape(-1))
                bs.append(getattr(self, "%s%d_h2h_bias" % (j, i))
                          .data().reshape(-1))
        return nd.concat(*(ws + bs), dim=0)

    def _forward_kernel(self, inputs, states):
        if self._layout == "NTC":
            inputs = nd.swapaxes(inputs, 0, 1)
        params = self._pack_params()
        rnn_args = [inputs, params] + list(states)
        out = nd.RNN(*rnn_args, state_size=self._hidden_size,
                     num_layers=self._num_layers,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True, mode=self._mode,
                     _training=autograd.is_training())
        if self._mode == "lstm":
            outputs, states = out[0], [out[1], out[2]]
        else:
            outputs, states = out[0], [out[1]]
        if self._layout == "NTC":
            outputs = nd.swapaxes(outputs, 0, 1)
        return outputs, states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (ref: rnn_layer.py:286 RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, dtype="float32", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation,
                         dtype=dtype, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (ref: rnn_layer.py:388 LSTM). States: [h, c]."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, dtype="float32", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm",
                         projection_size=projection_size, dtype=dtype,
                         **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (ref: rnn_layer.py:496 GRU); gate order [r, z, n]."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", dtype=dtype, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
