"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Blocks operating on HWC uint8/float images host-side (numpy/cv2) or on
device NDArrays. Compose chains them; ToTensor converts HWC uint8 ->
CHW float32/255 like the reference.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ...block import Block, HybridBlock
from ....ndarray import NDArray, array as nd_array

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "CropResize",
           "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomLighting", "RandomColorJitter"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Block):
    """ref: transforms.py Compose."""

    def __init__(self, transforms):
        super().__init__()
        self._transforms = transforms

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return nd_array(_to_np(x).astype(self._dtype))


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: transforms.py ToTensor)."""

    def forward(self, x):
        img = _to_np(x).astype(np.float32) / 255.0
        if img.ndim == 3:
            img = img.transpose(2, 0, 1)
        elif img.ndim == 4:
            img = img.transpose(0, 3, 1, 2)
        return nd_array(img)


class Normalize(Block):
    """(x - mean) / std on CHW images (ref: transforms.py Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def forward(self, x):
        img = _to_np(x).astype(np.float32)
        c = img.shape[-3]
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd_array((img - mean) / std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (list, tuple)) else (size, size)
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        import cv2
        img = _to_np(x)
        w, h = self._size
        if self._keep:
            ih, iw = img.shape[:2]
            scale = min(w / iw, h / ih)
            w, h = int(iw * scale + 0.5), int(ih * scale + 0.5)
        out = cv2.resize(img, (w, h), interpolation=self._interp)
        if out.ndim == 2:
            out = out[..., None]
        return nd_array(out)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (list, tuple)) else (size, size)
        self._interp = interpolation

    def forward(self, x):
        import cv2
        img = _to_np(x)
        cw, ch = self._size
        h, w = img.shape[:2]
        if h < ch or w < cw:
            img = cv2.resize(img, (max(w, cw), max(h, ch)),
                             interpolation=self._interp)
            h, w = img.shape[:2]
        y0, x0 = (h - ch) // 2, (w - cw) // 2
        out = img[y0:y0 + ch, x0:x0 + cw]
        if out.ndim == 2:
            out = out[..., None]
        return nd_array(out)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (list, tuple)) else (size, size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        import cv2
        img = _to_np(x)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = _pyrandom.uniform(*self._scale) * area
            ar = _pyrandom.uniform(*self._ratio)
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if cw <= w and ch <= h:
                x0 = _pyrandom.randint(0, w - cw)
                y0 = _pyrandom.randint(0, h - ch)
                crop = img[y0:y0 + ch, x0:x0 + cw]
                out = cv2.resize(crop, self._size,
                                 interpolation=self._interp)
                if out.ndim == 2:
                    out = out[..., None]
                return nd_array(out)
        return CenterCrop(self._size, self._interp)(nd_array(img))


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        img = _to_np(x)
        if _pyrandom.random() < self._p:
            img = img[:, ::-1].copy()
        return nd_array(img)


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        img = _to_np(x)
        if _pyrandom.random() < self._p:
            img = img[::-1].copy()
        return nd_array(img)


class _RandomJitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _alpha(self):
        return 1.0 + _pyrandom.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        return nd_array(_to_np(x).astype(np.float32) * self._alpha())


class RandomContrast(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype(np.float32)
        coef = np.array([0.299, 0.587, 0.114], np.float32)
        alpha = self._alpha()
        gray = (img * coef).sum(-1, keepdims=True)
        return nd_array(img * alpha + gray.mean() * (1 - alpha))


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype(np.float32)
        coef = np.array([0.299, 0.587, 0.114], np.float32)
        alpha = self._alpha()
        gray = (img * coef).sum(-1, keepdims=True)
        return nd_array(img * alpha + gray * (1 - alpha))


class CropResize(Block):
    """Crop a fixed region then optionally resize
    (ref: transforms.py:238 CropResize)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._x0, self._y0 = int(x), int(y)
        self._w, self._h = int(width), int(height)
        self._size = (size if isinstance(size, (list, tuple))
                      else (size, size)) if size is not None else None
        self._interp = interpolation

    def forward(self, x):
        import cv2
        img = _to_np(x)
        out = img[self._y0:self._y0 + self._h,
                  self._x0:self._x0 + self._w]
        if self._size is not None:
            out = cv2.resize(out, self._size, interpolation=self._interp)
        if out.ndim == 2:
            out = out[..., None]
        return nd_array(out)


class RandomHue(_RandomJitter):
    """Hue jitter via YIQ chroma rotation
    (ref: transforms.py:502 RandomHue / src/operator/image/image_random.cc
    RandomHue — same yiq rotation matrices)."""

    def forward(self, x):
        img = _to_np(x).astype(np.float32)
        alpha = _pyrandom.uniform(-self._amount, self._amount)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], np.float32)
        tyiq = np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], np.float32)
        ityiq = np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], np.float32)
        t = ityiq @ bt @ tyiq
        return nd_array(np.dot(img, t.T))


class RandomLighting(Block):
    """AlexNet-style PCA noise (ref: transforms.py RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        img = _to_np(x).astype(np.float32)
        a = np.random.normal(0, self._alpha, 3).astype(np.float32)
        rgb = (self._eigvec * a * self._eigval).sum(-1)
        return nd_array(img + rgb)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        ts = list(self._ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            x = t(x)
        return x
