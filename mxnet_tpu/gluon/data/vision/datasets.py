"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

Zero-egress environment: datasets read from a local `root` directory in the
standard file formats (idx-ubyte for MNIST, python pickles for CIFAR). When
files are absent and MXTPU_SYNTHETIC_DATA=1 is set, a deterministic
synthetic set with the right shapes/classes is generated so examples and
tests run offline.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..dataset import Dataset
from ....ndarray import array as nd_array
from ....base import getenv as _getenv

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synth_ok():
    return _getenv("MXTPU_SYNTHETIC_DATA", "0") == "1"


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        x = nd_array(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        _, n, h, w = struct.unpack(">IIII", f.read(16))
        return np.frombuffer(f.read(), np.uint8).reshape(n, h, w, 1)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        _, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), np.uint8).astype(np.int32)


class MNIST(_DownloadedDataset):
    """ref: datasets.py MNIST. Looks for train-images-idx3-ubyte[.gz] etc."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }
    _shape = (28, 28, 1)
    _classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        img, lab = self._files[self._train]
        for ext in ("", ".gz"):
            ip = os.path.join(self._root, img + ext)
            lp = os.path.join(self._root, lab + ext)
            if os.path.exists(ip) and os.path.exists(lp):
                self._data = _read_idx_images(ip)
                self._label = _read_idx_labels(lp)
                return
        if _synth_ok():
            # class-specific spatial patterns (a bright row band per
            # class) so example trainings converge fast on the synthetic
            # set — pure brightness coding makes features rank-1 and
            # training artificially slow
            n = 1024 if self._train else 256
            rng = np.random.RandomState(0 if self._train else 1)
            label = rng.randint(0, self._classes, n).astype(np.int32)
            data = (rng.rand(n, *self._shape) * 40.0)
            h = self._shape[0]
            band = max(h // self._classes, 1)
            for i in range(n):
                r0 = int(label[i]) * band % h
                data[i, r0:r0 + band] += 180.0
            self._data = np.clip(data, 0, 255).astype(np.uint8)
            self._label = label
            return
        raise IOError(
            "MNIST files not found under %s (offline build: place the "
            "idx-ubyte files there, or set MXTPU_SYNTHETIC_DATA=1)"
            % self._root)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """ref: datasets.py CIFAR10. Reads cifar-10-batches-py pickles."""

    _classes = 10
    _shape = (32, 32, 3)

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _batch_files(self):
        base = os.path.join(self._root, "cifar-10-batches-py")
        if self._train:
            return [os.path.join(base, "data_batch_%d" % i)
                    for i in range(1, 6)]
        return [os.path.join(base, "test_batch")]

    def _label_key(self):
        return b"labels"

    def _get_data(self):
        files = self._batch_files()
        if all(os.path.exists(f) for f in files):
            datas, labels = [], []
            for fn in files:
                with open(fn, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                datas.append(d[b"data"].reshape(-1, 3, 32, 32)
                             .transpose(0, 2, 3, 1))
                labels.extend(d[self._label_key()])
            self._data = np.concatenate(datas).astype(np.uint8)
            self._label = np.asarray(labels, np.int32)
            return
        if _synth_ok():
            n = 1024 if self._train else 256
            rng = np.random.RandomState(2 if self._train else 3)
            self._data = (rng.rand(n, *self._shape) * 255).astype(np.uint8)
            self._label = rng.randint(0, self._classes, n).astype(np.int32)
            return
        raise IOError("CIFAR files not found under %s (offline build: "
                      "place cifar-10-batches-py there, or set "
                      "MXTPU_SYNTHETIC_DATA=1)" % self._root)


class CIFAR100(CIFAR10):
    _classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 train=True, fine_label=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _batch_files(self):
        base = os.path.join(self._root, "cifar-100-python")
        return [os.path.join(base, "train" if self._train else "test")]

    def _label_key(self):
        return b"fine_labels" if self._fine else b"coarse_labels"


class ImageRecordDataset(Dataset):
    """Decoded images from a .rec file (ref: datasets.py ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = self._record[idx]
        header, img = unpack_img(record, self._flag)
        import cv2
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        x = nd_array(img)
        label = header.label
        if self._transform is not None:
            return self._transform(x, label)
        return x, label


class ImageFolderDataset(Dataset):
    """root/<class>/<image> layout (ref: datasets.py ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None,
                 exts=(".jpg", ".jpeg", ".png")):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fn in sorted(os.listdir(path)):
                if fn.lower().endswith(exts):
                    self.items.append((os.path.join(path, fn), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        import cv2
        fn, label = self.items[idx]
        img = cv2.imread(fn, cv2.IMREAD_COLOR if self._flag else
                         cv2.IMREAD_GRAYSCALE)
        if self._flag:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        x = nd_array(img if img.ndim == 3 else img[..., None])
        if self._transform is not None:
            return self._transform(x, label)
        return x, label
