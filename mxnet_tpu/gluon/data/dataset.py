"""Datasets (ref: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...ndarray import NDArray, array as nd_array

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset"]


class Dataset:
    """ref: dataset.py Dataset."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        kept = []
        for i in range(len(self)):
            item = self[i]
            if fn(item):
                kept.append(item)
        return SimpleDataset(kept)

    def shard(self, num_shards, index):
        assert 0 <= index < num_shards
        return SimpleDataset([self[i] for i in range(index, len(self),
                                                     num_shards)])

    def take(self, count):
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        def base_fn(x, *args):
            if args:
                return (fn(x),) + args
            return fn(x)
        return self.transform(base_fn, lazy)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    """Wrap any sized+indexable object (ref: dataset.py SimpleDataset)."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays (ref: dataset.py ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for d in args:
            assert len(d) == self._length, \
                "All arrays must have the same length"
            self._data.append(d)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Raw records from a .rec file (ref: dataset.py RecordFileDataset)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
