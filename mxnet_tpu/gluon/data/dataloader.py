"""DataLoader with parallel workers.

TPU-native redesign of the reference DataLoader
(ref: python/mxnet/gluon/data/dataloader.py — fork-based worker pool with
POSIX-shared-memory NDArray rebuild via src/storage/cpu_shared_storage_manager.h).
Design difference: the decode work here is numpy/cv2 (GIL-releasing), so the
default parallel path is a THREAD pool feeding a bounded prefetch queue —
no pickling, no shared-memory dance, and the accelerator transfer stays on
the main thread. num_workers>0 keeps the reference's meaning of concurrent
sample fetch; thread_pool=False switches to multiprocessing for Python-heavy
datasets.
"""
from __future__ import annotations

import concurrent.futures as _fut
import multiprocessing as _mp
import threading
import queue as _queue

import numpy as np

from ...ndarray import NDArray, array as nd_array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(i)) for i in zip(*data))
    arr = np.asarray(data)
    return nd_array(arr)


class DataLoader:
    """ref: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError("batch_size/shuffle/sampler/last_batch are "
                             "mutually exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch or 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _fetch_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._fetch_batch(indices)
            return
        if self._thread_pool:
            yield from self._iter_threaded()
        else:
            yield from self._iter_multiprocess()

    def _iter_threaded(self):
        with _fut.ThreadPoolExecutor(self._num_workers) as pool:
            batches = list(self._batch_sampler)
            futs = []
            depth = self._prefetch
            it = iter(batches)
            for indices in batches[:depth]:
                futs.append(pool.submit(self._fetch_batch, indices))
            submitted = min(depth, len(batches))
            for i in range(len(batches)):
                yield futs[i].result()
                if submitted < len(batches):
                    futs.append(pool.submit(self._fetch_batch,
                                            batches[submitted]))
                    submitted += 1

    def _iter_multiprocess(self):
        ctx = _mp.get_context("fork")
        with ctx.Pool(self._num_workers) as pool:
            batches = list(self._batch_sampler)
            # bounded in-flight window: at most `prefetch` decoded batches
            # pending, mirroring the threaded path (unbounded apply_async
            # would buffer the whole epoch in the parent)
            depth = max(self._prefetch, 1)
            pending = []
            submitted = 0
            for indices in batches[:depth]:
                pending.append(pool.apply_async(
                    _mp_fetch, (self._dataset, indices, self._batchify_fn)))
                submitted += 1
            for i in range(len(batches)):
                yield pending[i].get()
                if submitted < len(batches):
                    pending.append(pool.apply_async(
                        _mp_fetch, (self._dataset, batches[submitted],
                                    self._batchify_fn)))
                    submitted += 1


def _mp_fetch(dataset, indices, batchify_fn):
    return batchify_fn([dataset[i] for i in indices])
