"""DataLoader with parallel workers.

TPU-native redesign of the reference DataLoader
(ref: python/mxnet/gluon/data/dataloader.py — fork-based worker pool with
POSIX-shared-memory NDArray rebuild via src/storage/cpu_shared_storage_manager.h).
Design difference: the decode work here is numpy/cv2 (GIL-releasing), so the
default parallel path is a THREAD pool feeding a bounded prefetch queue —
no pickling, no shared-memory dance, and the accelerator transfer stays on
the main thread. num_workers>0 keeps the reference's meaning of concurrent
sample fetch; thread_pool=False switches to multiprocessing for Python-heavy
datasets.
"""
from __future__ import annotations

import concurrent.futures as _fut
import multiprocessing as _mp
import threading
import queue as _queue

import numpy as np

from ...ndarray import NDArray, array as nd_array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(i)) for i in zip(*data))
    arr = np.asarray(data)
    return nd_array(arr)


class DataLoader:
    """ref: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError("batch_size/shuffle/sampler/last_batch are "
                             "mutually exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch or 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _fetch_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._fetch_batch(indices)
            return
        if self._thread_pool:
            yield from self._iter_threaded()
        else:
            yield from self._iter_multiprocess()

    def _iter_threaded(self):
        with _fut.ThreadPoolExecutor(self._num_workers) as pool:
            batches = list(self._batch_sampler)
            futs = []
            depth = self._prefetch
            it = iter(batches)
            for indices in batches[:depth]:
                futs.append(pool.submit(self._fetch_batch, indices))
            submitted = min(depth, len(batches))
            for i in range(len(batches)):
                yield futs[i].result()
                if submitted < len(batches):
                    futs.append(pool.submit(self._fetch_batch,
                                            batches[submitted]))
                    submitted += 1

    def _iter_multiprocess(self):
        pool = self._get_pool()
        batches = list(self._batch_sampler)
        # bounded in-flight window: at most `prefetch` decoded batches
        # pending, mirroring the threaded path (unbounded apply_async
        # would buffer the whole epoch in the parent)
        depth = max(self._prefetch, 1)
        pending = []
        submitted = 0
        consumed = 0
        try:
            for indices in batches[:depth]:
                pending.append(pool.apply_async(
                    _mp_fetch_shm, (self._pool_key, indices)))
                submitted += 1
            for i in range(len(batches)):
                desc = pending[i].get()
                consumed = i + 1
                yield _from_shm(desc)
                if submitted < len(batches):
                    pending.append(pool.apply_async(
                        _mp_fetch_shm, (self._pool_key,
                                        batches[submitted])))
                    submitted += 1
        finally:
            # abandoned/broken iteration: reap in-flight batches and unlink
            # their shared-memory segments, otherwise they outlive the
            # process (workers hand tracker ownership to us). Short per-item
            # timeout: a dead pool must not freeze generator close.
            for r in pending[consumed:]:
                try:
                    _free_shm(r.get(timeout=5))
                except Exception:
                    pass

    def _get_pool(self):
        """Persistent fork-based worker pool — same lifecycle as the
        reference, which also keeps one pool for the DataLoader's lifetime
        (ref: gluon/data/dataloader.py DataLoader.__init__ worker_pool), so
        dataset mutations after the first epoch are likewise invisible to
        workers. The dataset is inherited by the forked children
        copy-on-write through a module-level registry — no per-task (or
        even per-worker) pickling — and batches come back through POSIX
        shared memory, the reference's CPUSharedStorageManager architecture
        (ref: src/storage/cpu_shared_storage_manager.h). The registry entry
        stays until shutdown so that workers respawned by Pool after an
        abnormal worker death still see every live loader's dataset."""
        if getattr(self, "_pool", None) is None:
            ctx = _mp.get_context("fork")
            self._pool_key = id(self)
            _WORKER_STATES[self._pool_key] = (self._dataset,
                                              self._batchify_fn)
            self._pool = ctx.Pool(self._num_workers)
            # tear the pool down before interpreter teardown starts —
            # mp.Pool.__del__ at shutdown races module globals going None
            import atexit
            import weakref
            ref = weakref.ref(self)

            def _atexit_cb():
                self_ = ref()
                if self_ is not None:
                    self_._shutdown_pool()

            self._atexit_cb = _atexit_cb
            atexit.register(_atexit_cb)
        return self._pool

    def _shutdown_pool(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            self._pool = None
            _WORKER_STATES.pop(getattr(self, "_pool_key", None), None)
            cb = getattr(self, "_atexit_cb", None)
            if cb is not None:
                self._atexit_cb = None
                import atexit
                try:
                    atexit.unregister(cb)
                except Exception:
                    pass
            try:
                pool.terminate()
                pool.join()
            except Exception:
                pass

    def __del__(self):
        self._shutdown_pool()


# {loader key: (dataset, batchify_fn)}, populated in the parent before the
# pool forks so children (and later respawns) inherit it without pickling
_WORKER_STATES = {}  # mxlint: disable=MX003 (parent-process registry keyed by id(loader): GIL-atomic writes to distinct keys, snapshotted into children at fork)


def _to_shm(obj):
    """Serialize a batch into shared-memory segment descriptors."""
    from multiprocessing import shared_memory
    if isinstance(obj, (tuple, list)):
        return ("tuple", [_to_shm(o) for o in obj])
    if isinstance(obj, NDArray):
        a = obj.asnumpy()
    elif isinstance(obj, np.ndarray):
        a = obj
    else:
        return ("obj", obj)
    a = np.ascontiguousarray(a)
    shm = shared_memory.SharedMemory(create=True, size=max(a.nbytes, 1))
    view = np.ndarray(a.shape, a.dtype, buffer=shm.buf)
    view[...] = a
    name = shm.name
    shm.close()
    # ownership passes to the parent (which unlinks after rebuild); drop the
    # worker-side resource_tracker registration so it does not warn about an
    # already-unlinked segment at worker exit
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:
        pass
    return ("shm", name, a.shape, str(a.dtype))


def _from_shm(desc):
    """Rebuild a batch from shared-memory descriptors (parent side)."""
    from multiprocessing import shared_memory
    tag = desc[0]
    if tag == "tuple":
        return tuple(_from_shm(o) for o in desc[1])
    if tag == "obj":
        return desc[1]
    _, name, shape, dtype = desc
    shm = shared_memory.SharedMemory(name=name)
    try:
        # one host memcpy out of the segment before unmapping: the device
        # transfer downstream is async and must not alias unmapped memory
        a = np.ndarray(shape, dtype, buffer=shm.buf).copy()
    finally:
        shm.close()
        shm.unlink()
    return nd_array(a)


def _free_shm(desc):
    """Unlink segments of a batch that will never be rebuilt."""
    from multiprocessing import shared_memory
    if desc[0] == "tuple":
        for o in desc[1]:
            _free_shm(o)
    elif desc[0] == "shm":
        try:
            shm = shared_memory.SharedMemory(name=desc[1])
            shm.close()
            shm.unlink()
        except Exception:
            pass


def _mp_fetch_shm(key, indices):
    dataset, batchify_fn = _WORKER_STATES[key]
    batch = batchify_fn([dataset[i] for i in indices])
    return _to_shm(batch)
