"""Gluon utility functions.

ref: python/mxnet/gluon/utils.py (split_data :31, split_and_load :81,
clip_global_norm :115, check_sha1 :159, download :190).

TPU-native note: `split_and_load` in the reference copies slices to per-GPU
contexts; on a TPU mesh, data parallelism shards the batch axis of ONE
logical array across devices (see mxnet_tpu.parallel). For API parity,
splitting across a ctx_list still returns per-slice NDArrays, and a
ctx_list of one context returns a single-element list.
"""
from __future__ import annotations

import hashlib
import math
import os

import jax.numpy as jnp
import numpy as _np

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "shape_is_known"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into `num_slice` slices along `batch_axis`
    (ref: gluon/utils.py:31)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data." % (
                str(data.shape), num_slice, batch_axis, num_slice))
    if num_slice == 1:
        return [data]
    step = size // num_slice
    if not even_split:
        slices = [
            data.slice_axis(batch_axis, i * step,
                            (i + 1) * step if i < num_slice - 1 else size)
            for i in range(num_slice)]
    else:
        slices = [data.slice_axis(batch_axis, i * step, (i + 1) * step)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data into len(ctx_list) slices and load each to one context
    (ref: gluon/utils.py:81)."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the sum of their 2-norms is at most max_norm
    (ref: gluon/utils.py:115)."""
    def _norm(array):
        if array.stype == "default":
            x = array.reshape((-1,))
            return nd.dot(x, x)
        return array.norm().square()

    assert len(arrays) > 0
    ctx = arrays[0].context
    total_norm = nd.add_n(*[_norm(arr).as_in_context(ctx) for arr in arrays])
    total_norm = nd.sqrt(total_norm)
    if check_isfinite:
        if not _np.isfinite(total_norm.asscalar()):
            import warnings
            warnings.warn(
                UserWarning("nan or inf is detected. Clipping results will "
                            "be undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    scale = nd.minimum(nd.ones(1, ctx=ctx), scale)
    for arr in arrays:
        arr._data = arr._data * scale._data.astype(arr.dtype)
    if check_isfinite:
        return total_norm.asscalar()
    return total_norm


def check_sha1(filename, sha1_hash):
    """Check whether the sha1 hash of the file matches (ref: utils.py:159)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Download a file (ref: gluon/utils.py:190). This environment has no
    egress; only file:// URLs and existing files resolve."""
    if path is None:
        fname = url.split("/")[-1]
        assert fname, ("Can't construct file-name from this URL. Please set "
                       "the `path` option manually.")
    else:
        path = os.path.expanduser(path)
        if os.path.isdir(path):
            fname = os.path.join(path, url.split("/")[-1])
        else:
            fname = path
    if url.startswith("file://"):
        src = url[len("file://"):]
        if overwrite or not os.path.exists(fname):
            import shutil
            os.makedirs(os.path.dirname(os.path.abspath(fname)), exist_ok=True)
            shutil.copyfile(src, fname)
        return fname
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise IOError(
        "download(%r): network egress is disabled in this environment; "
        "place the file at %r beforehand or use a file:// URL" % (url, fname))


def shape_is_known(shape):
    """ref: gluon/utils.py shape_is_known."""
    if shape is None:
        return False
    unknown_dim_size = -1
    if len(shape) == 0:
        return unknown_dim_size == -1
    for dim_size in shape:
        if dim_size in (unknown_dim_size, 0):
            return False
    return True


def _indent(s_, num_spaces):
    """Indent string for pretty-print (ref: gluon/utils.py _indent)."""
    s = s_.split("\n")
    if len(s) == 1:
        return s_
    first = s.pop(0)
    s = [first] + [(num_spaces * " ") + line for line in s]
    return "\n".join(s)
