"""Gluon losses.

Own-idiom rebuild of the reference's loss zoo
(ref: python/mxnet/gluon/loss.py). Nearly every loss there repeats the
same tail — optional per-sample weighting, then a mean over the
non-batch axes — so here that tail lives once (`_weighted` +
`_per_sample_mean`) and elementwise losses only state their term via
the `_ElementwiseLoss` template. All math goes through the F-dispatched
op layer (ops/), so a definition traces into one XLA program under
hybridize and runs eagerly otherwise; every reduction stays inside the
compiled graph — a loss never forces a device->host sync.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss"]

_EPS = 1e-12


def _weighted(F, term, weight, sample_weight):
    """The shared weighting tail: elementwise sample_weight (broadcast),
    then the loss's constant weight (ref helper: _apply_weighting)."""
    if sample_weight is not None:
        term = F.broadcast_mul(term, sample_weight)
    return term if weight is None else term * weight


def _softplus(F, x):
    """log(1 + exp(x)) via the op layer's softrelu activation."""
    return F.Activation(x, act_type="softrelu")


def _stable_bce(F, z, target):
    """Cross-entropy of sigmoid(z) against target without forming the
    sigmoid: max(z, 0) - z*target + log1p(exp(-|z|))."""
    return F.relu(z) - z * target + _softplus(F, -F.abs(z))


class Loss(HybridBlock):
    """Base: holds the constant weight and which axis indexes samples
    (ref: gluon/loss.py Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _per_sample_mean(self, F, term, sample_weight):
        """Weighting + mean over every axis except the batch one — the
        tail every elementwise loss shares."""
        term = _weighted(F, term, self._weight, sample_weight)
        return F.mean(term, axis=self._batch_axis, exclude=True)

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            type(self).__name__, self._batch_axis, self._weight)


class _ElementwiseLoss(Loss):
    """Template for losses of the form mean_over_sample(term(pred,
    label)): subclasses implement only `_term`; the label is first
    viewed in pred's shape (the reference reshapes likewise so int
    labels of shape [B] align with preds of [B, 1] etc.)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _term(self, F, pred, label):
        raise NotImplementedError

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        term = self._term(F, pred, label.reshape(pred.shape))
        return self._per_sample_mean(F, term, sample_weight)


class L2Loss(_ElementwiseLoss):
    """Half mean-squared error (the 1/2 makes the gradient pred-label)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _term(self, F, pred, label):
        # the constant 1/2 of the reference's weight/2 folded into the
        # term (scalars commute with the weighting tail)
        return 0.5 * F.square(label - pred)


class L1Loss(_ElementwiseLoss):
    def _term(self, F, pred, label):
        return F.abs(label - pred)


class HuberLoss(_ElementwiseLoss):
    """Quadratic inside |err| <= rho, linear outside."""

    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def _term(self, F, pred, label):
        err = F.abs(label - pred)
        return F.where(err > self._rho, err - 0.5 * self._rho,
                       F.square(err) * (0.5 / self._rho))


class HingeLoss(_ElementwiseLoss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def _term(self, F, pred, label):
        return F.relu(self._margin - pred * label)


class SquaredHingeLoss(HingeLoss):
    def _term(self, F, pred, label):
        return F.square(super()._term(F, pred, label))


class LogisticLoss(_ElementwiseLoss):
    """Binary logistic loss over raw scores; labels either {-1, 1}
    ("signed", default) or {0, 1} ("binary")."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise ValueError("label_format must be 'signed' or 'binary', "
                             "got %r" % (label_format,))
        self._label_format = label_format

    def _term(self, F, pred, label):
        if self._label_format == "signed":
            label = (label + 1.0) * 0.5  # {-1,1} -> {0,1}
        return _stable_bce(F, pred, label)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE over logits (default) or over already-sigmoided
    probabilities (from_sigmoid=True), with optional positive-class
    reweighting (ref: gluon/loss.py SigmoidBinaryCrossEntropyLoss)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = label.reshape(pred.shape)
        if self._from_sigmoid:
            pos_term = F.log(pred + _EPS) * label
            if pos_weight is not None:
                pos_term = F.broadcast_mul(pos_term, pos_weight)
            term = -(pos_term + F.log(1 - pred + _EPS) * (1 - label))
        elif pos_weight is None:
            term = _stable_bce(F, pred, label)
        else:
            # log-weight scales only the softplus branch, matching the
            # reference's weighted-logit algebra
            lw = 1 + F.broadcast_mul(pos_weight - 1, label)
            term = pred - pred * label \
                + lw * (_softplus(F, -F.abs(pred)) + F.relu(-pred))
        return self._per_sample_mean(F, term, sample_weight)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Categorical CE over logits; sparse int labels by default, dense
    distributions with sparse_label=False
    (ref: gluon/loss.py SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits \
            else F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            term = -F.pick(logp, label, axis=self._axis, keepdims=True)
        else:
            term = -F.sum(logp * label.reshape(logp.shape),
                          axis=self._axis, keepdims=True)
        return self._per_sample_mean(F, term, sample_weight)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """KL(label || softmax(pred)); pred is log-probabilities when
    from_logits (default), raw scores otherwise."""

    def __init__(self, from_logits=True, axis=-1, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits \
            else F.log_softmax(pred, axis=self._axis)
        term = label * (F.log(label + _EPS) - logp)
        return self._per_sample_mean(F, term, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification
    (ref: src/operator/nn/ctc_loss.cc + gluon/loss.py CTCLoss). The
    recursion itself is the registered ctc_loss op — a log-space
    forward pass over lax.scan, XLA-friendly (no warp-ctc kernel)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        from ..ndarray.register import invoke_by_name
        per_seq = invoke_by_name(
            "ctc_loss", pred, label, pred_lengths=pred_lengths,
            label_lengths=label_lengths, layout=self._layout,
            label_layout=self._label_layout)
        return _weighted(F, per_seq, self._weight, sample_weight)


class TripletLoss(Loss):
    """relu(margin + ||pos - a||^2 - ||neg - a||^2), one value per
    sample (already reduced, so only the weighting tail applies)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        gap = F.sum(F.square(positive.reshape(pred.shape) - pred)
                    - F.square(negative.reshape(pred.shape) - pred),
                    axis=self._batch_axis, exclude=True)
        return _weighted(F, F.relu(gap + self._margin), self._weight,
                         sample_weight)


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood; target * log(target!) tail via
    Stirling when compute_full (ref: gluon/loss.py PoissonNLLLoss —
    which reduces over EVERYTHING, batch included)."""

    _TWO_PI = 6.283185307179586

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-8):
        target = target.reshape(pred.shape)
        if self._from_logits:
            term = F.exp(pred) - target * pred
        else:
            term = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = (target * F.log(target + epsilon) - target
                        + 0.5 * F.log(self._TWO_PI * (target + epsilon)))
            term = term + F.where(target <= 1, F.zeros_like(target),
                                  stirling)
        return F.mean(_weighted(F, term, self._weight, sample_weight))


class CosineEmbeddingLoss(Loss):
    """1 - cos(a, b) for positive pairs, relu(cos - margin) for
    negative ones; returns one value per pair, unreduced like the
    reference."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        a = input1.reshape((input1.shape[0], -1))
        b = input2.reshape((input2.shape[0], -1))
        cos = F.sum(a * b, axis=1) / (
            F.norm(a, axis=1) * F.norm(b, axis=1) + _EPS)
        term = F.where(label.reshape((-1,)) == 1, 1 - cos,
                       F.relu(cos - self._margin))
        return _weighted(F, term, self._weight, sample_weight)
