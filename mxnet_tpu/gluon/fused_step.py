"""Fused train step: loss-forward + backward + optimizer update as ONE
donated jitted program.

The reference's biggest training-throughput lever is CachedOp with
``static_alloc``/``static_shape`` (ref: src/imperative/cached_op.cc —
plan memory once, reuse buffers, run the whole graph as one segment).
Our hybridize analog only jits the *forward*: backward replays the tape
as a separate vjp program and ``Trainer._update`` dispatches one
optimizer call per parameter per step, double-buffering weights and
optimizer state. For a ResNet/transformer step that host-side loop is
the dominant overhead — it spans autograd and the optimizer, so neither
the PR 1 eager fast path nor the HybridBlock cache can reach it.

``FusedTrainStep`` closes the loop: one ``jax.jit`` program traces

    loss = loss_fn(...)                  # forward
    grads = d loss / d params            # whole-graph backward (jax.vjp)
    w', s' = step_fn(w, g, s, lr, wd, r) # optimizer, all params at once

with parameter and optimizer-state buffers DONATED to XLA (off-CPU), so
weights update in place instead of being double-buffered — the
``static_alloc`` analog for the whole step. Per-step hyperparameters
(lr, wd, rescale_grad) enter as TRACED OPERANDS, never baked constants:
an lr schedule tick or a new ``batch_size`` divisor replays the same
executable (``fused_step.retraces == 0``). Programs are cached with the
same signature-keyed compile-on-repeat pattern as the imperative
dispatch cache (ndarray/register.py): a signature runs the genuine
eager path until it repeats, so one-shot shapes never pay a trace.

Anything the trace can't honor falls back to the eager
record/backward/``Trainer.step`` path for THAT step — never a crash —
and is tallied in ``fused_step.fallbacks``: the env kill switch
(``MXNET_GLUON_FUSED_STEP=0``), an active ``autograd.record`` scope, an
attached kvstore (multi-host reduce happens outside the program),
sparse grads, ``grad_req='add'``, a non-hybridized block handed to
``train_step``, optimizers without the pure ``step_fn`` form, and
deferred-init parameters (the eager step initializes them; later steps
fuse). Counters surface as ``profiler.metrics()['fused_step']`` and
each call is a ``gluon.train_step`` span in the profiler's ``gluon``
lane.

API::

    step = trainer.fuse_step(lambda x, y: loss(net(x), y))
    step = mxnet_tpu.gluon.train_step(net, loss, trainer)   # block form
    for x, y in batches:
        l = step(x, y, batch_size=x.shape[0])
"""
from __future__ import annotations

import functools
import inspect
import os
import time as _time
import warnings
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .. import profiler as _profiler
from ..base import getenv as _getenv
from .. import random as _random
from ..ndarray import NDArray
from ..ndarray import register as _register
from .._debug import faultpoint as _faultpoint
from .._debug import flightrec as _flightrec
from .._debug import healthmon as _healthmon
from .._debug import watchdog as _watchdog
from .. import storage as _storage
from ..optimizer.optimizer import _is_low_precision
from . import compile_cache as _compile_cache
from .block import make_pure_forward

__all__ = ["FusedTrainStep", "train_step", "fused_step_enabled",
           "set_fused_step", "stats", "reset_stats"]

_ENABLED = _getenv("MXNET_GLUON_FUSED_STEP", "1") \
    not in ("0", "false", "off")
# compile a signature only once it repeats (one-shot shapes stay on the
# genuine eager path) — same contract as register._JIT_THRESHOLD
_COMPILE_THRESHOLD = 2
_CACHE_CAP = 64  # per-step-object; shape churn clears rather than grows

# mxlint: disable=MX003 (GIL-atomic best-effort counters, same contract as ndarray/register._STATS)
_STATS = {
    "hits": 0,       # step served by a cached compiled program
    "misses": 0,     # signature not yet compiled (eager warming, or
                     # compiled this call)
    "retraces": 0,   # compile for a config seen before with different
                     # input/param avals — shape churn indicator
    "fallbacks": 0,  # step took the eager path for an eligibility or
                     # trace-failure reason (see the span's mode arg)
    "attr_errors": 0,  # compile-attribution bookkeeping failed after a
                       # committed compile step (telemetry lost, step kept)
    "health_errors": 0,  # healthmon.note_step raised after a committed
                         # program (sentinel verdict lost, step kept —
                         # a telemetry failure must not skip adoption)
    "mesh_fallbacks": 0,  # mesh-mode steps demoted to eager because the
                          # batch dim does not divide the 'dp' axis —
                          # every such step pays the single-device eager
                          # cost (the warn-once + flightrec marker make
                          # a 10x slowdown name itself)
}


def fused_step_enabled():
    return _ENABLED


def set_fused_step(enabled):
    """Toggle the fused train step at runtime (the env var
    ``MXNET_GLUON_FUSED_STEP`` sets the process default). Returns the
    previous value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(enabled)
    return prev


def stats():
    """Snapshot of the fused-step counters
    (hits/misses/retraces/fallbacks)."""
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


# surfaces as metrics()['fused_step'] and a dumps() line
_profiler.register_stats_provider("fused_step", stats, reset_stats)


# benchmark/comm_model.py is the ONE home of the wire-time formula and
# the v5e model assumptions (deduped there by the PR 7 review); it
# lives beside the package, not inside it, so load it by path — and
# degrade to attribution-less operation when the tree layout differs
# (an installed wheel without the benchmark/ dir).
_COMM_MODEL_UNSET = object()
_COMM_MODEL = _COMM_MODEL_UNSET


def _load_comm_model():
    global _COMM_MODEL
    if _COMM_MODEL is _COMM_MODEL_UNSET:
        try:
            import importlib.util
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "benchmark", "comm_model.py")
            spec = importlib.util.spec_from_file_location(
                "_mxtpu_comm_model", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _COMM_MODEL = mod
        except Exception:
            _COMM_MODEL = None
    return _COMM_MODEL


def _state_to_data(state):
    """NDArray state tree -> jax-array pytree (None passes through)."""
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state._data
    if isinstance(state, (tuple, list)):
        return tuple(_state_to_data(s) for s in state)
    return state


def _adopt_state(state, new):
    """Write a returned jax-array pytree back into the NDArray state
    tree in place (the pending-result adoption of optimizer state).
    Fresh buffers re-register in the allocation ledger; the replaced
    ones retire via weakref death / donation ``is_deleted()``."""
    if state is None:
        return
    if isinstance(state, NDArray):
        state._data = new
        _storage.ledger_register(new, "opt_state", site="fused_step")
        return
    for s, n in zip(state, new):
        _adopt_state(s, n)


def train_step(block, loss_fn, trainer, mesh=None, bucket_bytes=None,
               rules=None):
    """Fused training step for a (block, loss, trainer) triple:
    ``step(data, label, batch_size=...)`` computes
    ``loss_fn(block(data), label)``, backpropagates, and applies the
    trainer's optimizer — all inside one donated jitted program when the
    block is hybridized (eager fallback otherwise, tallied, never a
    crash). With more than two positional args, all but the last feed
    the block and the last is the label. Returns the loss NDArray, like
    the eager ``loss_fn`` call would.

    With ``mesh`` (a ``parallel.create_mesh`` DeviceMesh), the program
    runs data-parallel over the mesh's 'dp' axis inside ``shard_map``:
    the batch is sharded, parameters stay replicated, and the gradient
    all-reduce is issued as size-capped buckets placed MID-BACKWARD
    (``parallel/overlap.py``) so the reduction hides under the backward
    instead of serializing after it — the SCALING_r05 overlap story,
    folded into the fused step.

    With a 3D dp×tp×sp mesh (any model axis >1) or explicit ``rules``
    (regex partition rules over the param tree —
    ``parallel/sharding.PartitionRules``, a ``ShardingStrategy``, or a
    raw ``[(regex, spec)]`` list), the program runs in GSPMD mode
    instead: params carry NamedShardings from the rules, the batch is
    sharded over dp (and sp when it divides), the SPMD partitioner
    inserts the collectives, and the step's ``out_shardings`` are
    matched to its ``in_shardings`` so donated weights/optimizer state
    never reshard between steps (see docs/PARALLEL.md)."""
    return FusedTrainStep(trainer, loss_fn, block=block, mesh=mesh,
                          bucket_bytes=bucket_bytes, rules=rules)


class FusedTrainStep:
    """One training step as one XLA program (see the module docstring).

    Built via ``Trainer.fuse_step(loss_fn)`` (``loss_fn(*batch)`` is any
    callable over NDArrays returning the per-sample loss, usually a
    closure over the net) or ``gluon.train_step(block, loss_fn,
    trainer)``. In the closure form, parameters NOT owned by the trainer
    are baked into the program as constants — keep everything the loss
    reads inside the trainer (or use the block form, which threads every
    block parameter through the trace)."""

    def __init__(self, trainer, loss_fn, block=None, mesh=None,
                 bucket_bytes=None, rules=None):
        if not callable(loss_fn):
            raise TypeError("loss_fn must be callable, got %r"
                            % type(loss_fn))
        self._trainer = trainer
        self._block = block
        self._mesh = mesh
        self._bucket_bytes = bucket_bytes
        self._rules_arg = rules
        self._rules = None       # resolved PartitionRules (GSPMD mode)
        self._dp = 1
        self._sizes = {}
        self._mesh_n = 1
        self._warned_mesh_indivisible = False
        self._last_compiled = None  # most recent AOT executable (mesh)
        self._last_hlo = None       # ... and its optimized HLO text
        self._build_info = None     # contract facts of the last _build
        if mesh is not None:
            raw = getattr(mesh, "mesh", mesh)
            self._sizes = {a: int(s) for a, s in dict(raw.shape).items()}
            self._dp = int(self._sizes.get("dp", 1))
            self._mesh_n = 1
            for s in self._sizes.values():
                self._mesh_n *= int(s)
            # the Trainer/loss ce_local_accum weld: a mesh-aware loss
            # (e.g. a closure over parallel/transformer.loss_fn, which
            # auto-selects the single-reduction chunked CE) declares a
            # ``mesh`` kwarg and receives THIS step's mesh — no side
            # channel, the one mesh drives data, params and the loss
            try:
                if "mesh" in inspect.signature(loss_fn).parameters:
                    loss_fn = functools.partial(loss_fn, mesh=mesh)
            except (TypeError, ValueError):
                pass
        self._loss_fn = loss_fn
        self._cache = {}  # full signature ->
        #   (jfn, aux_params, fixed_pos, hmeta, in_shardings)
        self._key_counts = {}   # signature -> times seen (warming)
        self._partial_keys = set()  # configs compiled (retrace detection)
        self._failed_keys = set()   # signatures that failed to trace
        self.last_mode = None   # how the previous call executed
        self._aot = None        # (compiled, cost, hlo) from the last AOT
        self._ckey = None       # full signature key of the in-flight
        #                         compile; _run's AOT branch keys the
        #                         persistent compile cache by it
        self._aot_from_cache = False  # last AOT came off disk, so
        #                               _record_compile must not
        #                               re-serialize it back
        # signature -> modeled compute/comm split (ISSUE 8c): keyed like
        # _cache so a run alternating compiled signatures (main batch +
        # remainder shape) never subtracts the OTHER program's modeled
        # device time from this step's wall time
        self._attr_models = {}
        self._step_attr = None  # the executing step's model (set by hits)

    # -- mesh-mode selection -----------------------------------------------
    def _gspmd_mode(self):
        """True when this step compiles as one GSPMD program (jit with
        explicit in/out shardings) instead of the dp-only shard_map:
        any model axis of the mesh >1, or explicit partition rules.
        ``MXTPU_GSPMD_STEP=0`` (a compile-signature token) forces the
        legacy treatment — params replicated, batch dp-sharded — as the
        escape hatch for partitioner bugs; the token makes the flip
        land on a fresh cache key."""
        if self._mesh is None:
            return False
        model_axes = any(int(self._sizes.get(a, 1)) > 1
                         for a in ("tp", "sp", "fsdp", "ep", "pp"))
        if not (model_axes or self._rules_arg is not None):
            return False
        return _getenv("MXTPU_GSPMD_STEP", "1") not in ("0", "false",
                                                        "off")

    def _resolve_rules(self):
        """The partition rules the GSPMD mode shards params by: the
        constructor's ``rules`` (PartitionRules / ShardingStrategy /
        raw list), else inferred from the block's param paths
        (``sharding.infer_rules_for_block(..., 'auto')`` — Megatron TP
        rules when they match, replicated otherwise)."""
        if self._rules is not None:
            return self._rules
        from ..parallel import sharding as _sharding
        rules = self._rules_arg
        if rules is None:
            rules = _sharding.infer_rules_for_block(
                self._block, self._mesh, "auto")
        if isinstance(rules, _sharding.ShardingStrategy):
            rules = rules.param_rules
        elif not isinstance(rules, _sharding.PartitionRules):
            rules = _sharding.PartitionRules(rules)
        self._rules = rules
        return rules

    def last_program(self):
        """(compiled_executable, optimized_hlo_text) of the most recent
        AOT-compiled signature, or (None, None). The bench gspmd_step
        gate and the comm tests measure collective payloads from the
        HLO and check the matched-shardings contract on the
        executable."""
        return self._last_compiled, self._last_hlo

    def matched_step_shardings(self):
        """The SNIPPETS [1] zero-resharding contract, checked on the
        compiled program: the weight/optimizer-state OUTPUT shardings
        equal the corresponding INPUT shardings, so step N's donated
        outputs feed step N+1 without a single resharding transfer.
        Returns True/False, or None when no AOT program is held."""
        compiled = self._last_compiled
        if compiled is None:
            return None
        try:
            in_shs = compiled.input_shardings[0]
            out_shs = compiled.output_shardings
        except Exception:
            return None

        def _specs(tree):
            return [getattr(s, "spec", s) for s in
                    jax.tree_util.tree_leaves(tree)]

        n_train = len(_specs(in_shs[0]))
        n_state = len(_specs(in_shs[1]))
        # outputs: (loss, new_ws, new_sts, grads, aux[, health])
        return (_specs(out_shs[1]) == _specs(in_shs[0])
                and _specs(out_shs[2]) == _specs(in_shs[1])
                and n_train > 0 and n_state >= 0)

    # -- public ------------------------------------------------------------
    def __call__(self, *args, batch_size=None, ignore_stale_grad=False):
        from ..ndarray import array as _nd_array
        nd_args = [a if isinstance(a, NDArray) else _nd_array(a)
                   for a in args]
        if batch_size is None:
            batch_size = int(nd_args[0].shape[0]) \
                if nd_args and nd_args[0].shape else 1
        # watchdog beacon: the outermost in-flight step the stall
        # detector watches; non-"fused" completions are warm-up/compile/
        # fallback shapes and stay out of the rolling median
        _watchdog.step_begin()
        t0 = _time.perf_counter() if _profiler._LIVE else None
        mode = "error"
        try:
            loss, mode = self._dispatch(nd_args, batch_size,
                                        ignore_stale_grad)
        finally:
            self.last_mode = mode
            # mode rides the beacon so the goodput run ledger can split
            # step wall time into compute ('fused') vs compile
            # ('compile'/'eager-warming') vs host-bound fallbacks; the
            # executing program's signature tag rides along (one tuple
            # field) keying the watchdog window + the roofline join
            attr = self._step_attr if mode == "fused" else None
            _watchdog.step_end(warmup=mode != "fused", mode=mode,
                               sig=attr.get("sig") if attr else None)
            if t0 is not None:
                dur_us = (_time.perf_counter() - t0) * 1e6
                _profiler.record_op(
                    "gluon.train_step", dur_us,
                    category="gluon", lane="gluon",
                    args={"mode": mode, "batch_size": batch_size,
                          "params": len(self._trainer._params)})
                # the latency histogram ROADMAP item 1's serve gate
                # reports p50/p99 from (metrics()['latency'])
                _profiler.record_latency("fused_step.step", dur_us)
                if mode == "fused" and self._step_attr is not None:
                    # host share of THIS step = measured wall minus the
                    # modeled device time of the program that EXECUTED
                    # it — the latency series behind the dumps()
                    # attribution row
                    if self._step_attr["device_us"] > 0:
                        host = dur_us - self._step_attr["device_us"]
                        if host > 0:
                            _profiler.record_latency(
                                "fused_step.host_us", host)
                    # per-step memory.headroom gauge (ISSUE 13b): the
                    # EXECUTING signature's modeled peak vs the
                    # framework-side measured peak vs the device limit
                    # (cached snapshot — no backend walk per step)
                    if _profiler._ACTIVE and \
                            self._step_attr.get("peak_bytes"):
                        hr = _storage.headroom(
                            self._step_attr["peak_bytes"])
                        if hr:
                            _profiler.record_counter(
                                "memory.headroom", 0, lane="memory",
                                series=hr)
        return loss

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, nd_args, batch_size, ignore_stale_grad):
        reason = self._fallback_reason()
        if reason is None and self._mesh is not None and nd_args \
                and nd_args[0].shape \
                and nd_args[0].shape[0] % max(self._dp, 1) != 0:
            # the mesh step shards dim 0 over 'dp'; an indivisible batch
            # runs this step eagerly instead of crashing the trace.
            # Eager means SINGLE-DEVICE: a run whose loader emits such
            # batches silently pays ~mesh-size x per step, so the
            # demotion is never silent — a warn-once, a dedicated
            # counter, and a flight-recorder marker per occurrence
            reason = "mesh-batch-indivisible"
            _STATS["mesh_fallbacks"] += 1
            batch = int(nd_args[0].shape[0])
            if not self._warned_mesh_indivisible:
                self._warned_mesh_indivisible = True
                warnings.warn(
                    "fused step: batch dim %d does not divide mesh axis "
                    "dp=%d; this step (and every step with such a batch)"
                    " runs EAGERLY on one device. Pad or drop the "
                    "remainder batch, or size the loader batch to a "
                    "multiple of dp. (warn-once; see "
                    "fused_step.mesh_fallbacks in profiler.metrics())"
                    % (batch, self._dp), stacklevel=3)
            # mxlint: disable=MX011 (demotion path, not steady-state dispatch; the black box must see it with the profiler off)
            _flightrec.record_marker(
                "fused_step.mesh_fallback",
                args={"batch": batch, "dp": self._dp})
        if reason is None:
            all_params, train_pos, indices = self._param_split()
            if not train_pos:
                reason = "no-trainable-params"
            elif any(p._data is None for p in all_params):
                # covers block params the trainer does NOT own (frozen
                # layers): the eager step's forward finishes their
                # deferred init, later steps fuse
                reason = "deferred-init"
        if reason is not None:
            _STATS["fallbacks"] += 1
            return self._eager_step(nd_args, batch_size,
                                    ignore_stale_grad), \
                "fallback:" + reason

        # optimizer states are created HERE (not at update time) through
        # the trainer's own updater, so save_states/load_states round-trip
        # across eager and fused steps against one shared store
        updater = self._trainer._updater
        states = [updater.ensure_state(i, self._trainer._params[i].data())
                  for i in indices]
        key, partial = self._signature(nd_args, all_params, train_pos,
                                       states)
        if key in self._failed_keys:
            _STATS["fallbacks"] += 1
            return self._eager_step(nd_args, batch_size,
                                    ignore_stale_grad), \
                "fallback:trace-failed"

        entry = self._cache.get(key)
        if entry is not None:
            _STATS["hits"] += 1
            self._step_attr = self._attr_models.get(key)
            return self._run(entry, all_params, train_pos, indices, states,
                            nd_args, batch_size), "fused"

        _STATS["misses"] += 1
        if len(self._key_counts) >= 4 * _CACHE_CAP:
            self._key_counts.clear()  # one-shot signatures must not leak
        seen = self._key_counts.get(key, 0) + 1
        self._key_counts[key] = seen
        if seen < _COMPILE_THRESHOLD:
            return self._eager_step(nd_args, batch_size,
                                    ignore_stale_grad), "eager-warming"
        if len(self._cache) >= _CACHE_CAP:
            self._cache.clear()
            self._partial_keys.clear()
            self._attr_models.clear()
        if partial in self._partial_keys:
            _STATS["retraces"] += 1
        self._partial_keys.add(partial)
        try:
            c0 = _time.perf_counter()
            self._aot = None
            self._ckey = key
            entry = self._build(all_params, train_pos, nd_args, states)
            loss = self._run(entry, all_params, train_pos, indices, states,
                             nd_args, batch_size, aot=True)
            if self._aot is not None:
                # keep the AOT-compiled executable: jit's internal cache
                # does not share the AOT compilation, so calling the
                # plain jitted fn next step would compile a second time
                compiled, cost, hlo, mem = self._aot
                entry = (compiled,) + tuple(entry[1:])
                self._aot = None
            else:
                compiled = cost = hlo = mem = None
            compile_us = (_time.perf_counter() - c0) * 1e6
        except _healthmon.HealthHaltError:
            # a poisoned compile step under MXTPU_HEALTH_ACTION=halt is
            # a detected anomaly, not a trace failure: the batch must
            # NOT silently re-run on the eager path
            raise
        except Exception:
            # trace-incompatible step (data-dependent control flow, host
            # callback, ...): remember the signature and run the genuine
            # eager path — never a crash
            if len(self._failed_keys) >= 4 * _CACHE_CAP:
                self._failed_keys.clear()  # shape churn must not leak keys
            self._failed_keys.add(key)
            _STATS["fallbacks"] += 1
            return self._eager_step(nd_args, batch_size,
                                    ignore_stale_grad), \
                "fallback:trace-failed"
        self._cache[key] = entry
        # attribution AFTER the step committed, outside the trace-failure
        # try: the step above already mutated params/optimizer state, so a
        # cost-model or JAX-API error here must neither re-run the batch
        # eagerly (double update) nor blacklist a signature that compiled
        try:
            self._record_compile(key, compile_us, cost, hlo, mem,
                                 all_params, train_pos, states=states,
                                 compiled=compiled)
        except Exception:
            self._attr_models.pop(key, None)
            _STATS["attr_errors"] += 1
        return loss, "compile"

    def _fallback_reason(self):
        if not _ENABLED:
            return "disabled"
        if autograd.is_recording():
            return "recording-scope"
        tr = self._trainer
        # mirror the eager step() prologue so eligibility sees the real
        # kvstore/params state (both calls are idempotent)
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._params_to_init:
            tr._init_params()
        if tr._kvstore is not None:
            return "kvstore"
        if not tr._optimizer.fused_step_supported():
            return "optimizer:" + type(tr._optimizer).__name__
        if hasattr(tr, "_amp_loss_scaler"):
            # amp.init_trainer wraps Trainer._update with the dynamic
            # loss-scaler overflow skip — logic the fused program would
            # silently bypass
            return "amp-loss-scaler"
        if self._block is not None and \
                not getattr(self._block, "_active", False):
            return "non-hybridized"
        for p in tr._params:
            if p.grad_req == "add":
                return "grad-req-add"
            if getattr(p, "_grad_stype", "default") != "default" or \
                    getattr(p, "_stype", "default") != "default":
                return "sparse-grad"
        return None

    def _param_split(self):
        """(all_params, trainable positions, trainer indices). The block
        form threads EVERY block parameter through the trace (frozen ones
        as runtime inputs, not baked constants); the closure form can only
        see the trainer's."""
        tr = self._trainer
        if self._block is not None:
            all_params = self._block._all_params_list()
            known = {id(p) for p in all_params}
            all_params = all_params + [p for p in tr._params
                                       if id(p) not in known]
        else:
            all_params = list(tr._params)
        train_pos, indices = [], []
        for pos, p in enumerate(all_params):
            idx = tr._param2idx.get(p.name)
            if idx is not None and tr._params[idx] is p \
                    and p.grad_req != "null":
                train_pos.append(pos)
                indices.append(idx)
        return all_params, train_pos, indices

    def _signature(self, nd_args, all_params, train_pos, states):
        """(full cache key, partial key). lr/wd/rescale are operands and
        deliberately absent; the partial key (config without avals) is the
        retrace detector, same contract as register._dispatch_key."""
        state_datas = [_state_to_data(s) for s in states]
        mesh_fp = None
        if self._mesh is not None:
            # mode fingerprint: GSPMD vs dp-shard_map, the mesh axis
            # sizes, and (GSPMD) the partition-rule table — editing a
            # rule or resizing an axis must land on a fresh program,
            # never replay one compiled for another layout
            gspmd = self._gspmd_mode()
            mesh_fp = (gspmd, tuple(sorted(self._sizes.items())),
                       self._resolve_rules().describe() if gspmd
                       else None)
        partial = (self._trainer._optimizer._fused_static_key(),
                   len(all_params), tuple(train_pos),
                   mesh_fp,
                   _register._amp_version,
                   # the signature-token registry: every env var that
                   # changes a traced graph (the packed-apply toggle for
                   # the update phase, the kernel-routing envs for the
                   # forward) — flipping any of them mid-run must
                   # recompile, not silently replay the other form
                   _register.signature_tokens(),
                   jax.tree_util.tree_structure(state_datas))
        full = partial + (
            tuple(_register.aval(a._data) for a in nd_args),
            tuple(_register.aval(p.data()._data) for p in all_params),
            tuple(_register.aval(l)
                  for l in jax.tree_util.tree_leaves(state_datas)))
        return full, partial

    # -- the program -------------------------------------------------------
    def _build(self, all_params, train_pos, nd_args=None, states=None):
        """Trace loss-forward + backward + the optimizer update for ALL
        parameters into one pure function and jit it with weight and
        optimizer-state buffers donated (off-CPU; donation is a no-op on
        the host backend).

        Mesh modes (``nd_args``/``states`` supply the operand shapes the
        sharding trees need):

        - dp-only (``_gspmd_mode()`` False): the body is ``shard_map``-ped
          over 'dp' with the explicit psum bucket markers — byte-identical
          to the pre-3D program.
        - GSPMD (any model axis >1, or explicit rules): ONE ``jax.jit``
          whose ``in_shardings`` place params by the partition rules and
          the batch over dp×sp, and whose ``out_shardings`` pin the new
          weights/optimizer state to EXACTLY the input placements — step
          N's donated outputs are step N+1's inputs with zero resharding
          (the matched-shardings contract). The SPMD partitioner supplies
          every collective; the bucket markers run in their axis-free
          form so the reduction still lands per-bucket, and the chunked
          CE's own ``shard_map`` (``parallel/compat.py``) nests inside.
        """
        if _faultpoint.ACTIVE:
            # trace-site fault seam: _dispatch wraps _build in the
            # fallback:trace-failed try, so a raise here exercises the
            # per-step eager degradation a real trace failure takes
            _faultpoint.check("fused_step.trace")
        opt = self._trainer._optimizer
        gspmd = self._gspmd_mode()
        # manual_dp: the legacy dp-only shard_map treatment (explicit
        # axis, explicit psums); gspmd: plain jit + shardings, the
        # partitioner owns the collectives
        manual_dp = self._mesh is not None and not gspmd
        pure_fwd, aux_params = make_pure_forward(all_params, self._call,
                                                 training=True)
        n_all = len(all_params)
        train_set = set(train_pos)
        fixed_pos = tuple(i for i in range(n_all) if i not in train_set)
        mp = opt.multi_precision
        packed_apply = self._packed_apply_fn(opt, all_params, train_pos)

        # health sentinels (ISSUE 15) share the overlap bucket plan with
        # the mesh-mode reduction markers: dtype-homogeneous segments,
        # so the whole summary is a handful of fused reductions.
        # MXTPU_HEALTH / MXTPU_HEALTH_ACTION are signature tokens —
        # flipping either lands on a fresh cache key, never a replay of
        # the other graph.
        plan = None
        hmeta = None
        if self._mesh is not None or _healthmon.enabled():
            from ..parallel import overlap as _overlap
            plan = _overlap.bucket_plan(
                [all_params[pos].data()._data for pos in train_pos],
                self._bucket_bytes)
        if _healthmon.enabled():
            names = [all_params[pos].name for pos in train_pos]
            act = _healthmon.action()
            hmeta = {
                "plan": [list(b) for b in plan],
                "names": names,
                "bucket_names": [[names[i] for i in b] for b in plan],
                "action": act,
                # skip_step discards a poisoned update IN-GRAPH (the
                # only donation-safe place: once the program ran, the
                # old buffers are gone off-CPU); halt gets the same
                # select so a caught HealthHaltError leaves clean
                # weights behind
                "select": act in ("skip_step", "halt"),
                # digests are published for cross-rank SDC comparison
                # only when this program's grads are bitwise-shared
                # across ranks (the mesh-DP psum) — a local digest
                # would false-diverge every healthy step. Under GSPMD
                # rule-sharded params carry SHARDED grads, so digests
                # stay local there.
                "replicated": self._dp > 1 and not gspmd,
            }

        tag = None
        if self._mesh is not None:
            # mesh mode: bucket markers between the grad variables and
            # their use — each bucket's psum over 'dp' fires in the
            # backward the moment its segment completes, hiding the
            # reduction under the rest of the backward (overlap.py).
            # GSPMD form: axis_name=None — the markers keep the flat
            # per-bucket wire batching, the partitioner supplies the
            # reduction itself.
            from ..parallel import overlap as _overlap
            _tag_axis = "dp" if manual_dp else None

            def tag(tds):
                return tuple(_overlap.tag_gradient_buckets(
                    list(tds), _tag_axis, plan=plan, op="sum"))

        def pure_step(train_datas, state_datas, fixed_datas, in_datas,
                      lrs, wds, rescale, rng, corrupt=None):
            if manual_dp:
                # per-shard rng: a replicated key would hand every 'dp'
                # shard identical dropout masks (sample j of shard 0 and
                # shard 1 sharing a mask), shrinking the effective
                # randomness by the dp factor. The GSPMD program traces
                # GLOBALLY (no manual axis), so its one key already
                # draws per-sample masks — and matches the single-device
                # program bitwise.
                rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))

            def loss_of(tds):
                if tag is not None:
                    tds = tag(tds)
                merged = [None] * n_all
                for pos, d in zip(train_pos, tds):
                    merged[pos] = d
                for pos, d in zip(fixed_pos, fixed_datas):
                    merged[pos] = d
                outs, aux = pure_fwd(tuple(merged), in_datas, rng)
                # grad of sum(loss) ≙ backward's all-ones head seed;
                # in mesh mode the local-shard sums psum (via the
                # markers) into the identical full-batch gradient
                return jnp.sum(outs[0]), (outs[0], aux)

            (_, (loss, aux)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_datas)
            if hmeta is not None:
                # the health.grad.corrupt chaos seam: an exact
                # multiply-by-one identity on clean steps, NaN/inf/
                # bit-flip poison when the faultpoint armed the operand
                # — placed after the (mesh) reduction, so injected
                # corruption models post-reduction SDC
                grads = _healthmon.apply_corruption(grads, corrupt)
            # parity note: against the HYBRIDIZED eager path (backward =
            # vjp of the same jitted forward) this program is bitwise
            # identical; the non-hybridized per-op tape can differ by
            # ~1 ULP because XLA fuses tiny dots differently per context
            new_ws, new_sts = [None] * len(train_datas), \
                [None] * len(train_datas)
            packed_idx = packed_apply(train_datas, state_datas) \
                if packed_apply else []
            if packed_idx:
                # MXTPU_FUSED_APPLY: the packed multi-tensor apply —
                # dtype-homogeneous flat segments, ONE kernel launch
                # per bucket, bitwise-equal to the per-param chain
                # (pallas_kernels/optimizer_apply.py)
                from ..pallas_kernels import optimizer_apply as _oa
                pw, ps = _oa.packed_apply(
                    opt, [train_datas[i] for i in packed_idx],
                    [grads[i] for i in packed_idx],
                    [state_datas[i] for i in packed_idx],
                    [lrs[i] for i in packed_idx],
                    [wds[i] for i in packed_idx], rescale)
                for i, nw, ns in zip(packed_idx, pw, ps):
                    new_ws[i] = nw
                    new_sts[i] = ns
            for i in range(len(train_datas)):
                if new_ws[i] is not None:
                    continue
                w, g, st = train_datas[i], grads[i], state_datas[i]
                lr_i, wd_i, rs_i = lrs[i], wds[i], rescale
                if not (mp and _is_low_precision(w.dtype)) \
                        and w.dtype != jnp.float32:
                    # the eager per-param jit receives WEAK host scalars
                    # that demote to the weight dtype; traced operands
                    # are strong f32 — demote explicitly so fp16/bf16
                    # steps do the same low-precision arithmetic
                    lr_i = lr_i.astype(w.dtype)
                    wd_i = wd_i.astype(w.dtype)
                    rs_i = rs_i.astype(w.dtype)
                nw, ns = opt.step_fn_multi_precision(w, g, st, lr_i, wd_i,
                                                     rs_i)
                new_ws[i] = nw
                new_sts[i] = ns
            if manual_dp:
                # aux (BN moving stats) are per-shard estimates —
                # average them so every replica adopts the same value
                # (GSPMD computes them over the global batch already)
                from jax import lax
                aux = tuple(lax.pmean(a, "dp") for a in aux)
            if hmeta is None:
                return loss, tuple(new_ws), tuple(new_sts), grads, aux
            # health sentinels over the (reduced) grads, the PRE-update
            # weights (their reductions overlap the whole program
            # instead of extending the update's critical path — see
            # graph_summary) and the loss — a few fused sum reductions
            # threaded out as one extra tiny output
            health, ok = _healthmon.graph_summary(
                hmeta["plan"], grads, train_datas, loss,
                axis_name="dp" if manual_dp else None)
            if hmeta["select"]:
                # skip_step/halt: a poisoned update is discarded HERE,
                # where both the old and the new buffers still exist
                # (donation aliases them outside the program) — the
                # select is exact when ok, so the clean path stays
                # bitwise-identical
                new_ws = [jnp.where(ok, nw, w)
                          for nw, w in zip(new_ws, train_datas)]
                new_sts = jax.tree_util.tree_map(
                    lambda ns, s: jnp.where(ok, ns, s),
                    tuple(new_sts), tuple(state_datas))
            return loss, tuple(new_ws), tuple(new_sts), grads, aux, \
                health

        body = pure_step
        if manual_dp:
            from ..parallel.compat import PartitionSpec as P
            from ..parallel.compat import shard_map as _shard_map
            raw_mesh = getattr(self._mesh, "mesh", self._mesh)
            # params/states/hypers replicated, batch sharded on 'dp';
            # grads leave the body already psum'd (the markers), the
            # per-sample loss re-assembles across shards
            in_specs = (P(), P(), P(), P("dp"), P(), P(), P(), P())
            out_specs = (P("dp"), P(), P(), P(), P())
            if hmeta is not None:
                in_specs += (P(),)    # the corruption operand
                out_specs += (P(),)   # the (replicated) health summary
            body = _shard_map(
                pure_step, raw_mesh,
                in_specs=in_specs, out_specs=out_specs,
                check_vma=False)
        donate = ()
        try:
            if jax.default_backend() != "cpu":
                donate = (0, 1)  # weights + optimizer state
        except Exception:
            donate = ()
        in_shs = None
        if self._mesh is not None:
            in_shs = self._input_shardings(all_params, train_pos,
                                           fixed_pos, nd_args, states,
                                           hmeta is not None, gspmd)
        if gspmd:
            # the matched-shardings contract (out == in for donated
            # weights/optimizer state): the compiled program's weight
            # outputs land EXACTLY where the next step reads them.
            # Grads pin to the weight placements so adoption keeps the
            # layout the next backward consumes. loss/aux/health pin
            # REPLICATED (a tree-prefix sharding covers any rank):
            # bytes are trivial, and a multi-process mesh needs them
            # fully addressable on every rank (NDArray.asnumpy of a
            # cross-process-sharded loss cannot materialize).
            from ..parallel.compat import NamedSharding
            from ..parallel.compat import PartitionSpec as P
            rep = NamedSharding(getattr(self._mesh, "mesh", self._mesh),
                                P())
            out_shs = (rep, in_shs[0], in_shs[1], in_shs[0], rep)
            if hmeta is not None:
                out_shs += (rep,)
            jfn = jax.jit(body, in_shardings=in_shs,
                          out_shardings=out_shs,
                          donate_argnums=donate)
        else:
            jfn = jax.jit(body, donate_argnums=donate) if donate \
                else jax.jit(body)
        # contract facts the program-artifact capture (_record_compile →
        # profiler.record_program, the hlolint feed) needs but the entry
        # tuple doesn't carry: which operands were donated, whether this
        # is the GSPMD/manual-dp program, and which top-level output
        # slots were pinned replicated (loss=0, aux=4, health=5).
        self._build_info = {
            "donate": donate,
            "gspmd": bool(gspmd),
            "manual_dp": bool(manual_dp),
            "replicated_slots":
                ((0, 4, 5) if hmeta is not None else (0, 4))
                if gspmd else (),
        }
        return jfn, aux_params, fixed_pos, hmeta, in_shs

    def _input_shardings(self, all_params, train_pos, fixed_pos, nd_args,
                         states, with_corrupt, gspmd):
        """The operand-placement tree, structured EXACTLY like the
        operands tuple ``_run`` assembles (safe to bake at build time —
        the cache key pins every operand aval). dp-only mode reproduces
        the old placement shim: everything replicated, batch
        'dp'-sharded. GSPMD mode places each parameter by the partition
        rules (``PartitionRules.spec_for`` fits the spec to the shape
        and drops axes that don't divide), gives every optimizer-state
        leaf of weight shape the WEIGHT's placement (moments shard with
        their param) and replicates the rest (scalar counts), and
        shards the batch dim over 'dp' / the sequence dim over 'sp'
        when they divide."""
        from ..parallel.compat import NamedSharding, PartitionSpec as P
        raw_mesh = getattr(self._mesh, "mesh", self._mesh)
        rep = NamedSharding(raw_mesh, P())
        dp = max(int(self._sizes.get("dp", 1)), 1)
        sp = max(int(self._sizes.get("sp", 1)), 1)
        rules = self._resolve_rules() if gspmd else None

        def param_sh(pos):
            if not gspmd:
                return rep
            p = all_params[pos]
            shape = tuple(int(d) for d in p.data().shape)
            return NamedSharding(
                raw_mesh, rules.spec_for(p.name, shape, raw_mesh))

        def data_sh(a):
            if not gspmd:
                return NamedSharding(raw_mesh, P("dp"))
            shape = tuple(int(d) for d in a.shape)
            parts = []
            if shape:
                parts.append("dp" if dp > 1 and shape[0] % dp == 0
                             else None)
            if len(shape) > 1 and np.issubdtype(
                    np.dtype(getattr(a, "dtype", np.float32)),
                    np.integer):
                # dim 1 of an integer batch array is a token/sequence
                # dim — shard it over 'sp' (the chunked-CE loss path
                # consumes it sequence-parallel). Float dim 1 is a
                # FEATURE dim: sharding it would split contractions
                # into partial dots whose reordered sums break bitwise
                # parity with the unsharded program for zero benefit.
                parts.append("sp" if sp > 1 and shape[1] % sp == 0
                             else None)
            return NamedSharding(raw_mesh, P(*parts))

        train_shs = tuple(param_sh(pos) for pos in train_pos)
        state_shs = []
        for i, st in enumerate(states):
            wshape = tuple(int(d)
                           for d in all_params[train_pos[i]].data().shape)
            wsh = train_shs[i]
            state_shs.append(jax.tree_util.tree_map(
                lambda l, _w=wsh, _s=wshape:
                    _w if tuple(getattr(l, "shape", ())) == _s else rep,
                _state_to_data(st)))
        fixed_shs = tuple(param_sh(pos) for pos in fixed_pos)
        in_data_shs = tuple(data_sh(a) for a in nd_args)
        shs = (train_shs, tuple(state_shs), fixed_shs, in_data_shs,
               rep, rep, rep, rep)
        if with_corrupt:
            shs += (rep,)
        return shs

    def _packed_apply_fn(self, opt, all_params, train_pos):
        """The MXTPU_FUSED_APPLY eligibility selector, or None when the
        packed multi-tensor apply is off or the optimizer's step math
        is not packable (``Optimizer.fused_apply_supported``). The
        selector runs at trace time over the operand trees and returns
        the positions whose update goes through ``packed_apply`` —
        everything static (dtypes, state structure), so the decision
        bakes into the compiled program and the env toggle is part of
        the cache signature."""
        from ..pallas_kernels import optimizer_apply as _oa
        if not (_oa.enabled() and opt.fused_apply_supported()):
            return None
        mp = opt.multi_precision

        def select(train_datas, state_datas):
            idx, ref_struct = [], None
            for k, d in enumerate(train_datas):
                if mp and _is_low_precision(d.dtype):
                    continue  # (master, base) state: per-param path
                leaves = jax.tree_util.tree_leaves(state_datas[k])
                if any(l.shape != d.shape or l.dtype != d.dtype
                       for l in leaves):
                    continue
                struct = jax.tree_util.tree_structure(state_datas[k])
                if ref_struct is None:
                    ref_struct = struct
                elif struct != ref_struct:
                    continue
                idx.append(k)
            return idx
        return select

    @staticmethod
    def _place_operand(a, sh):
        """Move one operand onto its slot in the mesh placement tree.
        Already-placed arrays (every adopted output after step one, by
        the matched-shardings contract) pass through untouched. A
        single-process mesh takes the ``device_put`` fast path; a
        MULTI-PROCESS mesh is not addressable from one rank, so the
        global array is assembled shard-by-shard from this process's
        full local copy (every operand on this path is process-
        identical: params/state from the deterministic eager warmup,
        the full batch from the loader, host hyperparameter scalars)."""
        if getattr(a, "sharding", None) == sh:
            return a
        if getattr(sh, "is_fully_addressable", True):
            # mxlint: disable=MX018 (mesh re-placement of ALREADY-LEDGERED operands: the post-step adoption (_adopt_fused/_adopt_state) re-registers every surviving buffer; the replaced single-device ones retire via weakref death)
            return jax.device_put(a, sh)
        host = np.asarray(a)
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx])

    def _record_compile(self, key, dur_us, cost, hlo, mem, all_params,
                        train_pos, states=None, compiled=None):
        """Feed the compile-attribution registry (ISSUE 8c): measured
        trace+compile+first-run wall time, the program's cost-analysis
        flops/bytes, its collective payload, and the comm_model's
        modeled compute/comm times — the split that turns "step is
        slow" into "DCN all-reduce grew 40%". ``mem`` (ISSUE 13b) is
        the executable's ``memory_analysis()`` dict: its
        argument+output+temp total is the modeled HBM peak behind the
        ``memory.headroom`` gauge and the ``dumps()`` Memory table."""
        flops = bytes_acc = comm_bytes = comp_us = comm_us = None
        dtype = peak = None
        if cost:
            flops = float(cost.get("flops", 0.0)) or None
            bytes_acc = float(cost.get("bytes accessed", 0.0)) or None
        cm = _load_comm_model()
        if cm is not None:
            if hlo is not None:
                try:
                    comm_bytes = cm.collect_hlo_inventory(
                        hlo)["total_bytes"] or None
                except Exception:
                    comm_bytes = None
            if comm_bytes is None and self._dp > 1:
                # mesh mode without an inspectable HLO: the gradient
                # all-reduce payload is analytic — 4 bytes per trainable
                # f32 param (SCALING_r05's validated model)
                comm_bytes = 4 * sum(
                    int(all_params[pos].data().size)
                    for pos in train_pos)
            if flops:
                # the peak is keyed by the program's DOMINANT dtype
                # (by trainable-param bytes): an f32 net runs the MXU
                # at half the bf16 rate, an int8 one (the PR 9
                # quantized-matmul path) at double — a hardcoded bf16
                # peak halved/doubled every modeled compute time and
                # every MFU derived from it (ISSUE 17 satellite)
                dtype = self._dominant_dtype(all_params, train_pos)
                peak = cm.peak_tflops(dtype)
                comp_us = flops / (peak * 1e12) * 1e6
            if comm_bytes:
                comm_us = sum(cm.allreduce_seconds(
                    comm_bytes, max(self._dp, 2))) * 1e6 \
                    if self._dp > 1 else 0.0
        peak_bytes = None
        if mem is not None:
            # modeled resident peak while the program runs: live
            # arguments + outputs + XLA temp arena, minus the aliased
            # bytes — under donation (donate_argnums=(0,1) off-CPU) the
            # weight/opt-state outputs REUSE the argument buffers, and
            # memory_analysis counts those bytes on both sides with
            # alias_size recording the overlap. Generated code is
            # reported separately and lives outside HBM data space.
            peak_bytes = (mem.get("argument_bytes", 0)
                          + mem.get("output_bytes", 0)
                          + mem.get("temp_bytes", 0)
                          - mem.get("alias_bytes", 0))
            mem = dict(mem, peak_bytes=peak_bytes)
            _storage.note_modeled_peak("fused_step", peak_bytes)
        # the registry key must be STABLE across processes (ISSUE 17:
        # tools/perf_report.py --compare joins runs by signature tag):
        # crc32 of the signature tuple's repr, not the seed-randomized
        # builtin hash(). Avals, token strings and static-key entries
        # all repr deterministically.
        keyhash = "%08x" % (zlib.crc32(
            repr(key).encode("utf-8")) & 0xFFFFFFFF)
        self._attr_models.pop(key, None)
        if comp_us is not None or peak_bytes is not None:
            self._attr_models[key] = {
                "compute_us": comp_us or 0.0,
                "comm_us": comm_us or 0.0,
                "device_us": (comp_us or 0.0) + (comm_us or 0.0),
                "peak_bytes": peak_bytes,
                # the tag cache hits thread through watchdog.step_end:
                # same "name:key" string perfmodel derives from the
                # record_compile call below, so the roofline join's
                # two sides meet exactly
                "sig": "fused_step:%s" % keyhash,
            }
        _profiler.record_compile(
            "fused_step", key=keyhash,
            dur_us=dur_us, flops=flops, bytes_accessed=bytes_acc,
            comm_bytes=comm_bytes, modeled_compute_us=comp_us,
            modeled_comm_us=comm_us, memory=mem,
            args={"params": len(train_pos), "dp": self._dp,
                  "dtype": dtype, "peak_tflops": peak})
        if compiled is not None and _compile_cache.enabled() \
                and not self._aot_from_cache:
            # persist the executable for the NEXT process (ISSUE 19b);
            # skip when it just came off disk — re-serializing the same
            # entry buys nothing. store() is best-effort and counts its
            # own failures; a lost entry costs one recompile, never the
            # step.
            _compile_cache.store(key, compiled)
        if hlo is not None:
            # artifact capture (ISSUE 18): hand the HLO plus the
            # contract facts hlolint's H-rules check to the profiler's
            # program store. Everything is extracted EAGERLY into plain
            # Python so no record ever pins the executable.
            try:
                self._capture_program(keyhash, hlo, all_params,
                                      train_pos, states, compiled)
            except Exception:
                _STATS["attr_errors"] += 1

    def _capture_program(self, keyhash, hlo, all_params, train_pos,
                         states, compiled):
        """Build the hlolint program-meta dict for one compiled step and
        feed ``profiler.record_program``. The meta keys are the contract
        (tools/hlolint/capture.py documents them): ``donated`` — flat
        entry-parameter numbers that must appear in the input-output
        alias map (H001); ``plan`` — analytic per-kind collective bytes
        (H002, the same 4-bytes-per-trainable-param model the
        BENCH_MODEL=gspmd_step gate validated at <1%% wire error);
        ``replicated_slots``/``out_specs`` — top-level output slots
        pinned ``P()`` and the specs the executable actually carries
        (H003); ``dtype`` — the dominant param dtype keying the bf16
        upcast rule (H004)."""
        info = self._build_info or {}
        donated = ()
        if info.get("donate"):
            # donate_argnums=(0, 1) donates the train_datas and
            # state_datas tuples; their leaves are the leading entry
            # parameters of the flattened program, in order
            n_donated = len(train_pos)
            if states is not None:
                n_donated += len(jax.tree_util.tree_leaves(
                    [_state_to_data(s) for s in states]))
            donated = tuple(range(n_donated))
        plan = {"all-reduce": 0, "all-gather": 0, "reduce-scatter": 0,
                "collective-permute": 0, "all-to-all": 0}
        if self._mesh is not None and self._mesh_n > 1:
            plan["all-reduce"] = 4 * sum(
                int(all_params[pos].data().size) for pos in train_pos)
        out_specs = None
        if compiled is not None:
            try:
                out_specs = [
                    [tuple(getattr(sh, "spec", None) or ())
                     for sh in jax.tree_util.tree_leaves(slot)]
                    for slot in compiled.output_shardings]
            except Exception:
                out_specs = None
        _profiler.record_program(
            "fused_step", "fused_step:%s" % keyhash, hlo,
            meta={"donated": donated,
                  "plan": plan,
                  "replicated_slots":
                      tuple(info.get("replicated_slots", ())),
                  "out_specs": out_specs,
                  "dtype": self._dominant_dtype(all_params, train_pos),
                  "mesh": dict(self._sizes),
                  "gspmd": bool(info.get("gspmd"))})

    @staticmethod
    def _dominant_dtype(all_params, train_pos):
        """Short dtype key (``bf16``/``f32``/``int8``/...) of the
        dtype holding the majority of trainable-param bytes — what the
        program's matmuls actually run in, hence which MXU peak the
        modeled compute time must price against."""
        by_dtype = {}
        for pos in train_pos:
            d = all_params[pos].data()
            name = str(getattr(d, "dtype", None) or "float32")
            size = int(getattr(d, "size", 0))
            item = int(getattr(getattr(d, "dtype", None),
                               "itemsize", 4) or 4)
            by_dtype[name] = by_dtype.get(name, 0) + size * item
        if not by_dtype:
            return "bf16"
        dom = max(by_dtype, key=by_dtype.get)
        return {"float32": "f32", "bfloat16": "bf16",
                "float16": "f16", "int8": "int8",
                "float64": "f32"}.get(dom, "bf16")

    def _run(self, entry, all_params, train_pos, indices, states, nd_args,
             batch_size, aot=False):
        """Execute one fused step: host hyperparameter math (identical to
        the eager update()'s), the compiled program, then pending-result
        adoption back into Parameter.data()/grad() and the state store.
        With ``aot=True`` (the compile step) the program is lowered and
        compiled ahead-of-time so its ``cost_analysis()`` (flops/bytes)
        and optimized HLO feed the attribution registry; the compiled
        executable is kept (``self._aot``) and runs this step."""
        jfn, aux_params, fixed_pos, hmeta, in_shs = entry
        tr = self._trainer
        opt = tr._optimizer
        rescale = tr._scale / batch_size
        tr._check_and_rescale_grad(rescale)
        # count bookkeeping first, exactly like update(); snapshot so a
        # failing run (which then falls back to eager) can't double-count
        prev_num = opt.num_update
        prev_counts = {i: opt._index_update_count.get(i) for i in indices}
        opt._update_count(list(indices))

        def _rollback_counts():
            opt.num_update = prev_num
            for i, c in prev_counts.items():
                if c is None:
                    opt._index_update_count.pop(i, None)
                else:
                    opt._index_update_count[i] = c
        try:
            lrs = [opt.step_lr(i) for i in indices]
            wds = opt._get_wds(list(indices))
            train_params = [all_params[pos] for pos in train_pos]
            train_datas = tuple(p.data()._data for p in train_params)
            state_datas = tuple(_state_to_data(s) for s in states)
            fixed_datas = tuple(all_params[pos].data()._data
                                for pos in fixed_pos)
            in_datas = tuple(a._data for a in nd_args)
            # f32 operands: the framework canonicalizes float64 away at
            # the NDArray boundary (jax x64 stays off), so f32 is full
            # precision for every reachable weight dtype
            operands = (train_datas, state_datas, fixed_datas, in_datas,
                        jnp.asarray(lrs, jnp.float32),
                        jnp.asarray(wds, jnp.float32),
                        jnp.float32(rescale), _random.next_key())
            if hmeta is not None:
                # the health.grad.corrupt chaos operand: 0.0 on clean
                # steps (an exact in-graph multiply-by-one identity)
                operands = operands + (
                    jnp.float32(_healthmon.corruption_operand()),)
            if in_shs is not None:
                # mesh-mode placement: the first fused call receives
                # params/state committed to one device (their eager
                # birthplace); the mesh program spans every device, so
                # each operand moves to ITS slot in the placement tree
                # first. After step one the adopted outputs already
                # carry the matched out_shardings and every put is a
                # no-op — that is the zero-resharding contract. Also
                # what keeps AOT valid: the compiled executable demands
                # exactly these input shardings every call.
                operands = jax.tree_util.tree_map(
                    self._place_operand, operands, in_shs)
            runner = jfn
            if aot and hasattr(jfn, "lower"):
                # AOT lower+compile the compile step so the executable's
                # cost_analysis/HLO feed the attribution registry; the
                # cache key pins every operand aval (and mesh mode
                # pre-places operands above), so the executable stays
                # valid for all later hits of this signature.
                try:
                    # persistent cache first (ISSUE 19b): the key is the
                    # full signature _dispatch stashed in self._ckey —
                    # avals + signature-token snapshot + mesh
                    # fingerprint + optimizer static key — so a disk hit
                    # is exactly the executable this trace would have
                    # produced, and the trace+XLA compile is skipped
                    # entirely. Any load failure was counted by the
                    # cache and falls through to a fresh compile.
                    self._aot_from_cache = False
                    compiled = None
                    if _compile_cache.enabled() and self._ckey is not None:
                        compiled = _compile_cache.load(self._ckey)
                        self._aot_from_cache = compiled is not None
                    if compiled is None:
                        compiled = jfn.lower(*operands).compile()
                    cost = compiled.cost_analysis()
                    cost = cost[0] if isinstance(cost, (list, tuple)) \
                        else cost
                    try:
                        hlo = compiled.as_text()
                    except Exception:
                        hlo = None
                    mem = None
                    try:
                        # ISSUE 13b: the executable knows its own HBM
                        # footprint — argument/output/temp/generated
                        # bytes feed the compile registry's Memory
                        # table and the headroom gauge
                        ma = compiled.memory_analysis()
                        mem = {
                            "argument_bytes":
                                int(ma.argument_size_in_bytes),
                            "output_bytes":
                                int(ma.output_size_in_bytes),
                            "temp_bytes": int(ma.temp_size_in_bytes),
                            "alias_bytes":
                                int(ma.alias_size_in_bytes),
                            "generated_code_bytes":
                                int(ma.generated_code_size_in_bytes),
                        }
                    except Exception:
                        mem = None  # backend without memory_analysis
                    self._aot = (compiled, cost, hlo, mem)
                    if self._mesh is not None:
                        # the bench gspmd_step gate and the matched-
                        # shardings check read the most recent program
                        self._last_compiled = compiled
                        self._last_hlo = hlo
                    runner = compiled
                except Exception:
                    self._aot = None  # AOT API drift: plain path works
            if hmeta is not None:
                loss_data, new_ws, new_sts, grads, aux_datas, health = \
                    runner(*operands)
            else:
                loss_data, new_ws, new_sts, grads, aux_datas = \
                    runner(*operands)
        except BaseException:
            _rollback_counts()
            raise
        verdict = None
        if hmeta is not None:
            # the per-step sentinel check runs OUTSIDE the rollback
            # try: the program already committed (donated inputs are
            # gone off-CPU), so a raising telemetry path — a buggy
            # Monitor stat_func, a torn device_get — must neither skip
            # the adoption below nor take the training step down; it is
            # swallowed and counted. A halt verdict is RETURNED, never
            # raised here — adoption must run first (the selected
            # clean outputs are the only valid weights left).
            try:
                verdict = _healthmon.note_step(health, hmeta, grads,
                                               new_ws, batch_size)
            except Exception:
                _STATS["health_errors"] += 1
        halt = verdict.get("halt") if verdict else None
        skipped = bool(verdict and verdict.get("skipped")) \
            or halt is not None
        if skipped:
            # the poisoned update was discarded in-graph: host
            # bookkeeping follows, so the step bitwise never happened
            # (lr schedules keyed on num_update stay aligned with a run
            # that never saw the poisoned step)
            _rollback_counts()
        # pending-result adoption: weights + raw grads into the params,
        # state leaves into the updater's store, aux (moving stats) last
        # (under skip/halt the selected outputs ARE the old weight/state
        # values; the poisoned grads still adopt — next step's
        # post-mortem evidence, overwritten by the next backward)
        for p, nw, g in zip(train_params, new_ws, grads):
            p._adopt_fused(nw, g)
        for st, ns in zip(states, new_sts):
            _adopt_state(st, ns)
        if not skipped:
            for p, a in zip(aux_params, aux_datas):
                tgt = p.data()
                tgt._data = a if a.dtype == tgt.dtype \
                    else a.astype(tgt.dtype)
        if halt is not None:
            # adopt-then-raise: params/state now hold the clean
            # selected buffers on every backend, counts rolled back
            raise halt
        return NDArray(loss_data)

    # -- eager fallback ----------------------------------------------------
    def _call(self, *nd_args):
        if self._block is not None:
            if len(nd_args) >= 2:
                out = self._block(*nd_args[:-1])
                return self._loss_fn(out, nd_args[-1])
            return self._loss_fn(self._block(*nd_args))
        return self._loss_fn(*nd_args)

    def _unplace_mesh(self):
        """A mesh-fused step leaves params/grads/optimizer state
        replicated across the mesh; the eager path runs single-device
        programs, and mixing both commitments is a jit device error.
        Gather everything back to the default device before an eager
        step (rare: warming, indivisible batch, trace failure)."""
        dev = jax.devices()[0]

        def pull(a, tag):
            if a is None:
                return None
            sh = getattr(a, "sharding", None)
            if sh is not None and len(getattr(sh, "device_set", ())) > 1:
                gathered = jax.device_put(a, dev)
                # the gathered single-device buffer replaces a ledgered
                # one (which retires via weakref death) — re-register
                # under the same tag so unplacing never loses bytes
                _storage.ledger_register(gathered, tag,
                                         site="fused_step.unplace")
                return gathered
            return a

        def pull_nd(nd_, tag):
            if nd_ is not None and getattr(nd_, "_data", None) is not None:
                nd_._data = pull(nd_._data, tag)

        params = self._param_split()[0] if self._block is not None \
            else list(self._trainer._params)
        for p in params:
            pull_nd(p._data, "param")
            pull_nd(getattr(p, "_grad", None), "grad")
        upd = getattr(self._trainer, "_updater", None)
        if upd is not None:
            for st in upd.states.values():
                for leaf in jax.tree_util.tree_leaves(
                        st, is_leaf=lambda x: hasattr(x, "_data")):
                    pull_nd(leaf if hasattr(leaf, "_data") else None,
                            "opt_state")

    def _eager_step(self, nd_args, batch_size, ignore_stale_grad):
        """The untraced truth: record, backward, Trainer.step — used for
        warming runs and every fallback, so a fused-ineligible step is
        never a crash, just the eager cost."""
        if self._mesh is not None:
            self._unplace_mesh()
        with autograd.record():
            loss = self._call(*nd_args)
        if not isinstance(loss, NDArray):
            raise TypeError("loss_fn must return one NDArray loss, got %r"
                            % type(loss))
        autograd.backward([loss])
        self._trainer.step(batch_size, ignore_stale_grad=ignore_stale_grad)
        return loss
