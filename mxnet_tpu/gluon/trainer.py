"""Gluon Trainer: applies an Optimizer to a set of Parameters.

ref: python/mxnet/gluon/trainer.py:27 (Trainer, _init_kvstore :169,
step :305, allreduce_grads :334, update :365, save_states :436,
load_states :465).

TPU-native differences: the reference keeps one weight copy per GPU and
reduces gradients through the kvstore before updating every copy. Here a
Parameter is ONE logical array — under data parallelism it is replicated (or
sharded, FSDP-style) over the mesh by mxnet_tpu.parallel, and gradient
reduction happens inside the jitted step as an XLA collective. So
`allreduce_grads` is a no-op unless a multi-host kvstore is attached, and
`update` is the only real work: one fused optimizer step per parameter.
"""
from __future__ import annotations

import time as _time

from .. import optimizer as opt
from .. import profiler as _profiler
from ..ndarray import NDArray
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        param_list = []
        if isinstance(params, (dict,)) or hasattr(params, "items"):
            for key in sorted(list(params.keys())):
                param_list.append(params[key])
            params = param_list
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._param2idx[param.name] = i
            self._params.append(param)
            param._set_trainer(self)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore,
            "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._reset_kvstore()

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        # ONE updater owns all parameter state: a Parameter is one logical
        # (mesh-placed) array here, so the reference's updater-per-device
        # list collapses to a single update path — which is also the one
        # well-defined update list the fused step traces
        self._updater = opt.get_updater(self._optimizer)

    def _reset_kvstore(self):
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = [p for p in self._params]

    def _init_kvstore(self):
        """ref: trainer.py:169. Multi-host (dist_*) attaches a kvstore whose
        push performs the cross-process allreduce; in-process training needs
        none (collectives live inside the jitted step)."""
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        kv = None
        if kvstore and isinstance(kvstore, str) and \
                kvstore.startswith("dist"):
            from .. import kvstore as kvs
            kv = kvs.create(kvstore)
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if update_on_kvstore is None:
                update_on_kvstore = True
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        elif not isinstance(kvstore, str) and kvstore is not None:
            kv = kvstore  # user-provided KVStore object
            if update_on_kvstore is None:
                update_on_kvstore = False
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        else:
            update_on_kvstore = False
        self._kvstore = kv
        self._update_on_kvstore = bool(update_on_kvstore)
        self._kv_initialized = True

    def _init_params(self):
        for param in self._params_to_init:
            if param._deferred_init is not None:
                continue
            if self._kvstore is not None and param._data is not None:
                idx = self._param2idx[param.name]
                self._kvstore.init(idx, param.data())
        self._params_to_init = [p for p in self._params_to_init
                                if p._deferred_init is not None]

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate can be accessed.")
        if self._optimizer.lr_scheduler is not None:
            return self._optimizer.lr_scheduler(self._optimizer.num_update)
        return self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate is mutated.")
        self._optimizer.set_learning_rate(lr)

    def _check_and_rescale_grad(self, scale):
        """ref: trainer.py _check_and_rescale_grad — must happen BEFORE the
        kvstore pickles the optimizer (server-side copy sees the scale)."""
        if self._update_on_kvstore and self._kv_initialized and \
                self._optimizer.rescale_grad != scale:
            raise UserWarning(
                "Possible change in the `batch_size` from previous "
                "`step` detected. Optimizer gradient normalizing factor "
                "will not change w.r.t new batch_size when "
                "update_on_kvstore=True and when distributed kvstore is "
                "used.")
        self._optimizer.rescale_grad = scale

    def step(self, batch_size, ignore_stale_grad=False):
        """Make one parameter update: rescale by 1/batch_size, reduce, apply
        (ref: trainer.py:305). A span in the profiler's ``gluon`` lane when
        profiling is on — the per-step anchor the other lanes (imperative,
        bulk, kvstore, autograd, memory) line up under."""
        t0 = _time.perf_counter() if _profiler._LIVE else None
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update(ignore_stale_grad)
        if t0 is not None:
            _profiler.record_op(
                "gluon.Trainer.step", (_time.perf_counter() - t0) * 1e6,
                category="gluon", lane="gluon",
                args={"batch_size": batch_size,
                      "params": len(self._params)})

    def fuse_step(self, loss_fn, block=None, mesh=None, bucket_bytes=None,
                  rules=None):
        """Return a :class:`~mxnet_tpu.gluon.fused_step.FusedTrainStep`
        tracing ``loss_fn`` forward + backward + this trainer's optimizer
        update (all parameters at once) into ONE donated jitted program —
        the CachedOp ``static_alloc``/``static_shape`` analog for the
        whole training step. ``loss_fn(*batch)`` is any callable over
        NDArrays returning the per-sample loss, usually a closure over
        the net; parameters it reads that this trainer does not own are
        baked as constants (use ``gluon.train_step(block, loss, trainer)``
        to thread every block parameter through instead). Each call
        replaces the eager record/backward/``step`` triple and falls back
        to it per step whenever the trace can't honor the step (counted
        in ``profiler.metrics()['fused_step']``, never a crash).

        ``mesh`` runs the program data-parallel over the mesh's 'dp'
        axis with the gradient reduction bucketed and overlapped under
        the backward (``bucket_bytes`` caps each bucket; default
        ``MXTPU_ELASTIC_BUCKET_MB``) — see ``gluon.train_step``.
        Mesh-mode caveat: inside ``shard_map`` BatchNorm normalizes
        with per-shard (local-batch) statistics and pmean's the moving
        stats — standard DDP semantics, but NOT what the eager warmup
        steps (global batch) compute; BN-dependent models should make
        the per-device batch large enough or use a cross-replica
        norm.

        A mesh with model axes (tp/sp > 1), or an explicit ``rules``
        (regex → PartitionSpec partition rules, see
        ``parallel/sharding.match_partition_rules``), selects the GSPMD
        form instead: ONE jit program whose in/out shardings place the
        params by the rules and keep step N's donated outputs exactly
        where step N+1 reads them (zero resharding between steps); a
        mesh-aware ``loss_fn`` (one declaring a ``mesh`` kwarg) receives
        this mesh, which is how ``parallel.transformer.loss_fn``
        auto-selects the single-reduction chunked CE."""
        from .fused_step import FusedTrainStep
        return FusedTrainStep(self, loss_fn, block=block, mesh=mesh,
                              bucket_bytes=bucket_bytes, rules=rules)

    def allreduce_grads(self):
        """Explicit reduce step for when update() is called separately
        (ref: trainer.py:334)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "allreduce_grads() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` to False " \
            "when creating trainer."
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                idx = self._param2idx[param.name]
                grad = param.grad()
                if getattr(param, "_grad_stype", "default") == \
                        "row_sparse":
                    # ship only touched rows (ref: kvstore_dist.h:522);
                    # indices come from an on-device nonzero, so the
                    # conversion never syncs the dense grad to host
                    from ..ndarray.sparse import RowSparseNDArray
                    grad = RowSparseNDArray(grad._data, ctx=grad._ctx)
                if self._update_on_kvstore:
                    self._kvstore.pushpull(idx, grad,
                                           out=param.data(), priority=-i)
                else:
                    self._kvstore.push(idx, grad, priority=-i)
                    self._kvstore.pull(idx, param.grad(), priority=-i,
                                       ignore_sparse=False)

    def update(self, batch_size, ignore_stale_grad=False):
        """Make one step using gradients already reduced
        (ref: trainer.py:365)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._check_and_rescale_grad(self._scale / batch_size)
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updates = []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            fresh = getattr(param.data(), "_fresh_grad", True)
            if not fresh:
                if not ignore_stale_grad:
                    raise UserWarning(
                        "Gradient of Parameter `%s` on context %s has not "
                        "been updated by backward since last `step`. This "
                        "could mean a bug in your model that made it only "
                        "use a subset of the Parameters (Blocks) for this "
                        "iteration. If you are intentionally only using a "
                        "subset, call step with ignore_stale_grad=True to "
                        "suppress this warning" % (
                            param.name, str(param.data().context)))
                # ref: trainer.py:365 skips non-fresh grads under
                # ignore_stale_grad instead of re-applying the previous
                # iteration's gradient (momentum would keep charging)
                continue
            if self._kvstore and self._update_on_kvstore:
                # the kvstore's pushpull already applied this update in
                # _allreduce_grads (and a failed pushpull raised before
                # reaching here) — only now is the grad consumed
                param.data()._fresh_grad = False
                continue
            updates.append((i, param.grad(), param.data()))
        if updates:
            i, g, w = zip(*updates)
            self._updater(list(i), list(g), list(w))
            # age grads only after the update path actually ran: a
            # raising updater must leave them fresh so a retried step
            # doesn't trip the stale-grad check (or silently skip params)
            for data in w:
                data._fresh_grad = False

    def save_states(self, fname):
        """Save optimizer/updater states (ref: trainer.py:436).
        Crash-consistent: temp-file + atomic rename (base.atomic_write),
        so an interrupted save never truncates the previous states file
        a resume depends on."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            assert not self._params_to_init, \
                "Cannot save trainer states when some parameters are not " \
                "yet initialized in kvstore."
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            from ..base import atomic_write
            with atomic_write(fname) as fout:
                fout.write(self._updater.get_states(dump_optimizer=True))

    def load_states(self, fname):
        """ref: trainer.py:465."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
            self._optimizer = self._updater.optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
