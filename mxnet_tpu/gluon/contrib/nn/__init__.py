"""Gluon contrib layers (ref: python/mxnet/gluon/contrib/nn/__init__.py)."""
from .basic_layers import *  # noqa: F401,F403
