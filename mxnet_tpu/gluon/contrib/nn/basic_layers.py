"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py:
Concurrent :31, HybridConcurrent :64, Identity :97, SparseEmbedding :118,
SyncBatchNorm :165, PixelShuffle1D/2D/3D :244/:292/:354)."""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock
from .... import ndarray as nd

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(nn.Sequential):
    """Run children on the same input and concat outputs
    (ref: basic_layers.py:34 Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent (ref: basic_layers.py:73)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        # HybridSequential.forward would CHAIN children; used by both the
        # eager path and the cached-op trace.
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block, for use in Concurrent branches
    (ref: basic_layers.py:112 Identity)."""

    def forward(self, x, *args):
        return x


class SparseEmbedding(nn.Embedding):
    """Embedding with row-sparse gradient API (ref: basic_layers.py:118).

    On TPU the gradient is computed dense (XLA has no sparse tensors;
    docs/PARITY.md) but the layer keeps the reference's name and
    constructor so model code ports unchanged."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer, **kwargs)

    def __repr__(self):
        return "SparseEmbedding(%d -> %d)" % (self._input_dim,
                                              self._output_dim)


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device synchronized BatchNorm (ref: basic_layers.py:165).

    The reference synchronizes batch statistics with an explicit key-value
    AllReduce across GPUs (src/operator/contrib/sync_batch_norm-inl.h).
    Here the TPU story is structural: inside a pjit'd step over a mesh the
    batch axis is sharded and XLA turns the batch-stat reductions into
    cross-replica collectives automatically, so the same layer IS
    synchronized when compiled over a mesh; `num_devices` is accepted for
    API parity."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        self._num_devices = num_devices
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class PixelShuffle1D(HybridBlock):
    """[N, f*C, W] -> [N, C, W*f] (ref: basic_layers.py:244)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = int(factor)

    def forward(self, x, *args):
        f = self._factor
        n, fc, w = x.shape
        c = fc // f
        y = x.reshape((n, c, f, w))            # (N, C, f, W) — C major,
        y = y.transpose((0, 1, 3, 2))          # like the reference :283
        return y.reshape((n, c, w * f))

    def __repr__(self):
        return "PixelShuffle1D(%d)" % self._factor


class PixelShuffle2D(HybridBlock):
    """[N, f1*f2*C, H, W] -> [N, C, H*f1, W*f2] (ref: basic_layers.py:292)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = (int(factor),) * 2
        except TypeError:
            self._factors = tuple(int(f) for f in factor)
            assert len(self._factors) == 2

    def forward(self, x, *args):
        f1, f2 = self._factors
        n, fc, h, w = x.shape
        c = fc // (f1 * f2)
        y = x.reshape((n, c, f1, f2, h, w))    # C major (ref :344-347)
        y = y.transpose((0, 1, 4, 2, 5, 3))    # (N, C, H, f1, W, f2)
        return y.reshape((n, c, h * f1, w * f2))

    def __repr__(self):
        return "PixelShuffle2D(%s)" % (self._factors,)


class PixelShuffle3D(HybridBlock):
    """[N, f1*f2*f3*C, D, H, W] -> [N, C, D*f1, H*f2, W*f3]
    (ref: basic_layers.py:354)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = (int(factor),) * 3
        except TypeError:
            self._factors = tuple(int(f) for f in factor)
            assert len(self._factors) == 3

    def forward(self, x, *args):
        f1, f2, f3 = self._factors
        n, fc, d, h, w = x.shape
        c = fc // (f1 * f2 * f3)
        y = x.reshape((n, c, f1, f2, f3, d, h, w))  # C major (ref :407-415)
        y = y.transpose((0, 1, 5, 2, 6, 3, 7, 4))   # (N,C,D,f1,H,f2,W,f3)
        return y.reshape((n, c, d * f1, h * f2, w * f3))

    def __repr__(self):
        return "PixelShuffle3D(%s)" % (self._factors,)
