"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py:
Concurrent, HybridConcurrent, Identity)."""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock
from .... import ndarray as nd

__all__ = ["Concurrent", "HybridConcurrent", "Identity"]


class Concurrent(nn.Sequential):
    """Run children on the same input and concat outputs
    (ref: basic_layers.py:34 Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent (ref: basic_layers.py:73)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        # HybridSequential.forward would CHAIN children; used by both the
        # eager path and the cached-op trace.
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block, for use in Concurrent branches
    (ref: basic_layers.py:112 Identity)."""

    def forward(self, x, *args):
        return x
