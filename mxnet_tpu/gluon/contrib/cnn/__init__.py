"""Gluon contrib CNN layers (ref: python/mxnet/gluon/contrib/cnn/)."""
from .conv_layers import DeformableConvolution  # noqa: F401

__all__ = ["DeformableConvolution"]
