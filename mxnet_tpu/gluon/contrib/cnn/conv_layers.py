"""Contrib convolution layers
(ref: python/mxnet/gluon/contrib/cnn/conv_layers.py:29).

The DeformableConvolution block owns BOTH convolutions of the v1 design:
the plain conv that predicts the sampling offsets and the deformable
conv that consumes them (op: ops/detection.py deformable_convolution —
bilinear taps gathered per static kernel position, one grouped MXU
matmul)."""
from __future__ import annotations

from ....base import numeric_types
from ...block import HybridBlock
from ...nn.basic_layers import Activation

__all__ = ["DeformableConvolution"]


def _tup2(v):
    return (v,) * 2 if isinstance(v, numeric_types) else tuple(v)


class DeformableConvolution(HybridBlock):
    """2-D Deformable Convolution v1 (Dai et al. 2017): a regular conv
    learns per-position sampling offsets for the main conv
    (ref: gluon/contrib/cnn/conv_layers.py:29)."""

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 op_name="DeformableConvolution", adj=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("NCHW",), \
            "deformable convolution supports NCHW layout"
        kernel_size = _tup2(kernel_size)
        strides = _tup2(strides)
        padding = _tup2(padding)
        dilation = _tup2(dilation)
        self._channels = channels
        self._in_channels = in_channels
        self._op_name = op_name

        offset_channels = 2 * kernel_size[0] * kernel_size[1] \
            * num_deformable_group
        self._kwargs_offset = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": offset_channels,
            "num_group": groups, "layout": layout}
        self._kwargs_deform = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "num_deformable_group": num_deformable_group}

        self.offset_weight = self.params.get(
            "offset_weight",
            shape=(offset_channels, in_channels // groups if in_channels
                   else 0) + kernel_size,
            init=offset_weight_initializer, allow_deferred_init=True)
        self.offset_bias = self.params.get(
            "offset_bias", shape=(offset_channels,),
            init=offset_bias_initializer,
            allow_deferred_init=True) if offset_use_bias else None
        self.deformable_conv_weight = self.params.get(
            "deformable_conv_weight",
            shape=(channels, in_channels // groups if in_channels else 0)
            + kernel_size,
            init=weight_initializer, allow_deferred_init=True)
        self.deformable_conv_bias = self.params.get(
            "deformable_conv_bias", shape=(channels,),
            init=bias_initializer,
            allow_deferred_init=True) if use_bias else None
        self.act = Activation(activation) if activation else None
        self._groups = groups
        self._kernel = kernel_size

    def _shape_hint(self, x, *args):
        cin = x.shape[1]
        hints = {
            self.offset_weight:
                (self._kwargs_offset["num_filter"],
                 cin // self._groups) + self._kernel,
            self.deformable_conv_weight:
                (self._channels, cin // self._groups) + self._kernel,
        }
        if self.offset_bias is not None:
            hints[self.offset_bias] = (self._kwargs_offset["num_filter"],)
        if self.deformable_conv_bias is not None:
            hints[self.deformable_conv_bias] = (self._channels,)
        return hints

    def hybrid_forward(self, F, x, offset_weight, deformable_conv_weight,
                       offset_bias=None, deformable_conv_bias=None):
        offset = F.Convolution(x, offset_weight, offset_bias,
                               no_bias=offset_bias is None,
                               **self._kwargs_offset)
        out = F.DeformableConvolution(
            x, offset, deformable_conv_weight, deformable_conv_bias,
            no_bias=deformable_conv_bias is None, **self._kwargs_deform)
        if self.act is not None:
            out = self.act(out)
        return out

    def _alias(self):
        return "deformable_conv"
