"""Text dataset constants (ref: gluon/contrib/data/_constants.py)."""
EOS_TOKEN = "<eos>"
