"""Contrib samplers (ref: python/mxnet/gluon/contrib/data/sampler.py)."""
from __future__ import annotations

from ...data import sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(sampler.Sampler):
    """Samples [0, length) at fixed ``interval`` strides; with
    ``rollover`` the walk restarts at each skipped offset so every index
    is visited (ref: gluon/contrib/data/sampler.py:25)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, \
            "Interval %s must be <= length %s" % (interval, length)
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        starts = range(self._interval if self._rollover else 1)
        for start in starts:
            yield from range(start, self._length, self._interval)

    def __len__(self):
        return self._length
