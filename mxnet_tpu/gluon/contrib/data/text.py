"""Contrib text datasets (ref: python/mxnet/gluon/contrib/data/text.py).

WikiText2/WikiText103 keep the reference API (root/segment/vocab/
seq_len, `<eos>` per line, contiguous next-token labels reshaped to
fixed-length rows). This build is zero-egress: the loader reads the
standard ``wiki.<segment>.tokens`` file if present under ``root``;
setting ``MXTPU_SYNTHETIC_DATA=1`` (opt-in, same convention as the
vision datasets, gluon/data/vision/datasets.py) substitutes a
deterministic synthetic corpus; otherwise a missing file raises."""
from __future__ import annotations

import io
import os

import numpy as np

from ....contrib import text
from ...data import dataset
from . import _constants as C
from ....base import getenv as _getenv

__all__ = ["WikiText2", "WikiText103"]


def _synth_ok():
    # opt-in, matching the vision datasets: a mistyped root must raise,
    # not silently train on the fake corpus
    return _getenv("MXTPU_SYNTHETIC_DATA", "0") == "1"


class _LanguageModelDataset(dataset.Dataset):
    """ref: gluon/contrib/data/text.py:35 _LanguageModelDataset."""

    def __init__(self, root, namespace, vocabulary):
        self._root = os.path.expanduser(root)
        self._vocab = vocabulary
        self._counter = None
        self._namespace = namespace
        self._data = None
        self._label = None
        self._get_data()

    @property
    def vocabulary(self):
        return self._vocab

    @property
    def frequencies(self):
        return self._counter

    def _build_vocab(self, content):
        if not self._counter:
            self._counter = text.utils.count_tokens_from_str(content)
        if not self._vocab:
            self._vocab = text.vocab.Vocabulary(
                counter=self.frequencies, reserved_tokens=[C.EOS_TOKEN])


class _WikiText(_LanguageModelDataset):

    def _synth_corpus(self):
        """Deterministic Markov-ish corpus standing in for the download
        (zero-egress CI)."""
        rng = np.random.RandomState(
            {"train": 0, "validation": 1, "test": 2}[self._segment])
        words = ["the", "of", "and", "in", "to", "a", "was", "is", "for",
                 "on", "as", "by", "with", "at", "from", "wiki", "text",
                 "language", "model", "data"]
        n_lines = {"train": 400, "validation": 80, "test": 80}[self._segment]
        lines = []
        for _ in range(n_lines):
            ln = rng.randint(5, 25)
            lines.append(" ".join(words[rng.randint(len(words))]
                                  for _ in range(ln)))
        return "\n".join(lines)

    def _read_content(self):
        fname = "wiki.%s.tokens" % (
            "valid" if self._segment == "validation" else self._segment)
        path = os.path.join(self._root, fname)
        if os.path.exists(path):
            with io.open(path, "r", encoding="utf8") as fin:
                return fin.read()
        if _synth_ok():
            return self._synth_corpus()
        raise IOError(
            "%s not found under %s (offline build: place the WikiText "
            "tokens files there, or set MXTPU_SYNTHETIC_DATA=1)"
            % (fname, self._root))

    def _get_data(self):
        content = self._read_content()
        self._build_vocab(content)
        raw = [ln.strip().split() for ln in content.splitlines()]
        raw = [ln for ln in raw if ln]
        for ln in raw:
            ln.append(C.EOS_TOKEN)
        flat = self.vocabulary.to_indices(
            [tok for ln in raw for tok in ln if tok])
        data = np.array(flat[:-1], dtype=np.int32)
        label = np.array(flat[1:], dtype=np.int32)
        n = (len(data) // self._seq_len) * self._seq_len
        from .... import ndarray as nd
        self._data = nd.array(data[:n].reshape((-1, self._seq_len)),
                              dtype="int32")
        self._label = nd.array(label[:n].reshape((-1, self._seq_len)),
                               dtype="int32")

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


class WikiText2(_WikiText):
    """WikiText-2 word-level LM dataset
    (ref: gluon/contrib/data/text.py:105)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-2"),
                 segment="train", vocab=None, seq_len=35):
        self._segment = segment
        self._seq_len = seq_len
        super().__init__(root, "wikitext-2", vocab)


class WikiText103(_WikiText):
    """WikiText-103 word-level LM dataset
    (ref: gluon/contrib/data/text.py:143)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-103"),
                 segment="train", vocab=None, seq_len=35):
        self._segment = segment
        self._seq_len = seq_len
        super().__init__(root, "wikitext-103", vocab)
