"""Convolutional recurrent cells (ref: python/mxnet/gluon/contrib/rnn/
conv_rnn_cell.py — _BaseConvRNNCell :37, Conv{1,2,3}DRNNCell :218+,
Conv{1,2,3}DLSTMCell :473+, Conv{1,2,3}DGRUCell :762+).

NCHW-family layouts only (the TPU compute path is layout-agnostic under
XLA; the reference's NHWC option is accepted but normalized)."""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, dims):
    return (v,) * dims if isinstance(v, int) else tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery: i2h/h2h convolutions over spatial states
    (ref: conv_rnn_cell.py:37)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._dims = dims
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        assert all(k % 2 == 1 for k in self._h2h_kernel), \
            "h2h_kernel must be odd so the state keeps its spatial shape " \
            "(got %s)" % (h2h_kernel,)
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_dilate = _tup(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in zip(
            self._h2h_dilate, self._h2h_kernel))

        in_c = self._input_shape[0]
        spatial = self._input_shape[1:]
        out_spatial = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad,
                                  self._i2h_dilate, self._i2h_kernel))
        self._state_shape = (hidden_channels,) + out_spatial

        ng = self._num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(ng * hidden_channels, in_c)
            + self._i2h_kernel, init=i2h_weight_initializer,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(ng * hidden_channels, hidden_channels)
            + self._h2h_kernel, init=h2h_weight_initializer,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_channels,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_channels,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NC" + "DHW"[-self._dims:]}] * self._n_states

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel,
                            stride=(1,) * self._dims,
                            pad=self._i2h_pad, dilate=self._i2h_dilate,
                            num_filter=ng * self._hidden_channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel,
                            stride=(1,) * self._dims,
                            pad=self._h2h_pad, dilate=self._h2h_dilate,
                            num_filter=ng * self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    """out = act(conv(x) + conv(h)) (ref: conv_rnn_cell.py:177)."""

    _gate_names = ("",)
    _n_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    """Shi et al. 2015 convolutional LSTM (ref: conv_rnn_cell.py:420)."""

    _gate_names = ("_i", "_f", "_c", "_o")
    _n_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.Activation(slices[0], act_type="sigmoid")
        forget_gate = F.Activation(slices[1], act_type="sigmoid")
        in_transform = self._get_activation(F, slices[2], self._activation)
        out_gate = F.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c,
                                                 self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    """Convolutional GRU (ref: conv_rnn_cell.py:704)."""

    _gate_names = ("_r", "_z", "_o")
    _n_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_o = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_o = F.split(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        new = self._get_activation(F, i2h_o + reset * h2h_o,
                                   self._activation)
        next_h = (1.0 - update) * new + update * states[0]
        return next_h, [next_h]


def _make(base, dims, name, default_act):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout=None, activation=default_act, prefix=None,
                 params=None):
        base.__init__(self, input_shape=input_shape,
                      hidden_channels=hidden_channels,
                      i2h_kernel=i2h_kernel, h2h_kernel=h2h_kernel,
                      i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                      h2h_dilate=h2h_dilate,
                      i2h_weight_initializer=i2h_weight_initializer,
                      h2h_weight_initializer=h2h_weight_initializer,
                      i2h_bias_initializer=i2h_bias_initializer,
                      h2h_bias_initializer=h2h_bias_initializer,
                      dims=dims, conv_layout=conv_layout,
                      activation=activation, prefix=prefix, params=params)

    cls = type(name, (base,), {"__init__": __init__,
                               "__doc__": "%dD %s (ref: conv_rnn_cell.py)"
                               % (dims, base.__doc__.splitlines()[0])})
    return cls


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell", "tanh")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell", "tanh")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell", "tanh")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell", "tanh")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell", "tanh")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell", "tanh")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell", "leaky")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell", "leaky")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell", "leaky")
