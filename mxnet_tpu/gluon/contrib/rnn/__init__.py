"""Contrib recurrent cells (ref: python/mxnet/gluon/contrib/rnn/)."""
from .rnn_cell import *        # noqa: F401,F403
from .conv_rnn_cell import *   # noqa: F401,F403
