"""Keras-like training facade (ref:
python/mxnet/gluon/contrib/estimator/__init__.py)."""
from .estimator import Estimator  # noqa: F401
from .event_handler import *  # noqa: F401,F403
