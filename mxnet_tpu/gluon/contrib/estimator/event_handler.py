"""Estimator event handlers (ref:
python/mxnet/gluon/contrib/estimator/event_handler.py).

Same lifecycle protocol as the reference: handlers implement any of the
TrainBegin/TrainEnd/EpochBegin/EpochEnd/BatchBegin/BatchEnd mixins and are
dispatched by the Estimator at the matching points of the fit loop.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as _np

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch epochs or max_batch batches
    (ref: event_handler.py StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.max_epoch = self.max_epoch or estimator.max_epoch
        self.max_batch = self.max_batch or estimator.max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics each epoch, update each batch
    (ref: event_handler.py MetricHandler)."""

    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []
        self.priority = -_np.inf  # run first

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.train_metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        for metric in self.train_metrics:
            from ....metric import Loss as _Loss
            if isinstance(metric, _Loss):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic validation runs (ref: event_handler.py ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Log training progress (ref: event_handler.py LoggingHandler)."""

    LOG_PER_EPOCH = 1
    LOG_PER_BATCH = 2

    def __init__(self, log_interval="epoch", metrics=None, priority=_np.inf):
        self.metrics = metrics or []
        self.log_interval = log_interval
        self.priority = priority  # run last so metrics are updated
        self.batch_index = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        estimator.logger.info("Training begin: using optimizer %s with "
                              "current learning rate %.4f",
                              estimator.trainer.optimizer.__class__.__name__,
                              estimator.trainer.learning_rate)
        if estimator.max_epoch:
            estimator.logger.info("Train for %d epochs.", estimator.max_epoch)
        else:
            estimator.logger.info("Train for %d batches.",
                                  estimator.max_batch)

    def train_end(self, estimator, *args, **kwargs):
        train_time = time.time() - self.train_start
        msg = "Train finished using total %ds with %d epochs. " % (
            train_time, self.current_epoch)
        for metric in self.metrics:
            name, value = metric.get()
            msg += "%s: %.4f, " % (name, value)
        estimator.logger.info(msg.rstrip(", "))

    def batch_begin(self, estimator, *args, **kwargs):
        if self.log_interval == "batch" or \
                self.log_interval == self.LOG_PER_BATCH:
            self.batch_start = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        if self.log_interval == "batch" or \
                self.log_interval == self.LOG_PER_BATCH:
            batch_time = time.time() - self.batch_start
            msg = "[Epoch %d][Batch %d] " % (self.current_epoch,
                                             self.batch_index)
            msg += "time/batch: %.3fs " % batch_time
            for metric in self.metrics:
                name, value = metric.get()
                msg += "%s: %.4f, " % (name, value)
            estimator.logger.info(msg.rstrip(", "))
        self.batch_index += 1

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        epoch_time = time.time() - self.epoch_start
        msg = "[Epoch %d] finished in %.3fs: " % (self.current_epoch,
                                                  epoch_time)
        for metric in self.metrics:
            name, value = metric.get()
            msg += "%s: %.4f, " % (name, value)
        estimator.logger.info(msg.rstrip(", "))
        self.current_epoch += 1
        self.batch_index = 0


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save model/trainer state periodically, keeping the best by a
    monitored metric (ref: event_handler.py CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.verbose = verbose
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.saved_checkpoints = []
        self.current_epoch = 0
        self.current_batch = 0
        if save_best and monitor is None:
            raise ValueError("save_best requires a monitor metric")
        if mode == "min" or (mode == "auto" and monitor is not None
                             and "loss" in monitor.get()[0]):
            self.monitor_op = _np.less
            self.best = _np.inf
        else:
            self.monitor_op = _np.greater
            self.best = -_np.inf

    def train_begin(self, estimator, *args, **kwargs):
        if not os.path.exists(self.model_dir):
            os.makedirs(self.model_dir)

    def _save(self, estimator, tag):
        prefix = os.path.join(self.model_dir, self.model_prefix)
        param_path = "%s-%s.params" % (prefix, tag)
        estimator.net.save_parameters(param_path)
        trainer_path = "%s-%s.states" % (prefix, tag)
        estimator.trainer.save_states(trainer_path)
        self.saved_checkpoints.append(tag)
        while len(self.saved_checkpoints) > self.max_checkpoints:
            old = self.saved_checkpoints.pop(0)
            for suffix in (".params", ".states"):
                path = "%s-%s%s" % (prefix, old, suffix)
                if os.path.exists(path):
                    os.remove(path)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, "batch%d" % self.current_batch)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, "epoch%d" % self.current_epoch)
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            if self.monitor_op(value, self.best):
                self.best = value
                prefix = os.path.join(self.model_dir, self.model_prefix)
                estimator.net.save_parameters("%s-best.params" % prefix)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving
    (ref: event_handler.py EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        if mode == "min" or (mode == "auto" and "loss" in monitor.get()[0]):
            self.monitor_op = _np.less
        else:
            self.monitor_op = _np.greater
        if self.monitor_op == _np.greater:
            self.min_delta *= 1
        else:
            self.min_delta *= -1

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.best = self.baseline if self.baseline is not None else (
            _np.inf if self.monitor_op == _np.less else -_np.inf)

    def epoch_end(self, estimator, *args, **kwargs):
        _, value = self.monitor.get()
        if self.monitor_op(value - self.min_delta, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                estimator.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            estimator.logger.info("[Epoch %d] EarlyStoppingHandler: "
                                  "early stopping due to %s not improving",
                                  self.stopped_epoch, self.monitor.get()[0])
