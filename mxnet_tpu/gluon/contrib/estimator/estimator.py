"""Estimator: Keras-like fit loop over Gluon models
(ref: python/mxnet/gluon/contrib/estimator/estimator.py).

Same API as the reference; the train step — forward, loss, backward,
update — runs through the standard autograd/Trainer path, so a hybridized
network executes as one fused XLA program per batch."""
from __future__ import annotations

import copy
import logging

from .... import autograd
from ....metric import EvalMetric, Loss as MetricLoss, Accuracy
from ... import Trainer
from ...loss import Loss as GluonLoss
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, LoggingHandler, ValidationHandler)

__all__ = ["Estimator"]


class Estimator:
    """ref: estimator.py:44 Estimator."""

    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        self.stop_training = False
        if isinstance(loss, GluonLoss):
            self.loss = [loss]
        elif isinstance(loss, (list, tuple)) and \
                all(isinstance(l, GluonLoss) for l in loss):
            self.loss = list(loss)
        else:
            raise ValueError("loss must be a Loss or a list of Loss, "
                             "got %s" % type(loss))
        self.train_metrics = self._check_metrics(metrics)
        if not self.train_metrics:
            self.train_metrics = [Accuracy()]
        # one Loss metric per loss fn (ref: estimator.py _add_default_training_metrics)
        for l in self.loss:
            self.train_metrics.append(
                MetricLoss(name=l.__class__.__name__.lower()))
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        for m in self.val_metrics:
            m.name = "validation " + m.name

        self.logger = logging.getLogger("Estimator")
        self.logger.setLevel(logging.INFO)

        from ....context import current_context
        self.context = context if context is not None else [current_context()]
        if not isinstance(self.context, (list, tuple)):
            self.context = [self.context]
        self._initialize(initializer)
        self.trainer = trainer if trainer is not None else Trainer(
            self.net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.max_epoch = None
        self.max_batch = None

    @staticmethod
    def _check_metrics(metrics):
        if metrics is None:
            return []
        metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        if not all(isinstance(m, EvalMetric) for m in metrics):
            raise ValueError("metrics must be EvalMetric instances")
        return list(metrics)

    def _initialize(self, initializer):
        params = self.net.collect_params()
        uninitialized = any(p._data is None and p._deferred_init is None
                            for p in params.values())
        if uninitialized or initializer is not None:
            try:
                self.net.initialize(init=initializer, force_reinit=False)
            except Exception:  # already initialized
                pass

    # -- evaluation -------------------------------------------------------
    def evaluate_batch(self, val_batch, val_metrics, batch_axis=0):
        data, label = val_batch
        pred = self.net(data)
        loss = [l(pred, label) for l in self.loss]
        for metric in val_metrics:
            if isinstance(metric, MetricLoss):
                metric.update(0, loss)
            else:
                metric.update(label, pred)

    def evaluate(self, val_data, val_metrics=None, batch_axis=0):
        """Run validation (ref: estimator.py evaluate)."""
        val_metrics = val_metrics or self.val_metrics
        for metric in val_metrics:
            metric.reset()
        for batch in val_data:
            self.evaluate_batch(self._unpack(batch), val_metrics, batch_axis)
        return val_metrics

    # -- training ---------------------------------------------------------
    @staticmethod
    def _unpack(batch):
        if hasattr(batch, "data"):  # DataBatch
            data = batch.data[0]
            label = batch.label[0] if batch.label else None
            return data, label
        data, label = batch[0], batch[1]
        return data, label

    def fit_batch(self, train_batch, batch_axis=0):
        """One train step (ref: estimator.py fit_batch)."""
        data, label = self._unpack(train_batch)
        with autograd.record():
            pred = self.net(data)
            loss = [l(pred, label) for l in self.loss]
        for l in loss:
            l.backward()
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        """ref: estimator.py fit — epochs or batches bound the run."""
        if not (epochs is None) != (batches is None):
            raise ValueError("one and only one of epochs or batches "
                             "must be specified")
        self.max_epoch = epochs
        self.max_batch = batches
        self.stop_training = False

        event_handlers = self._prepare_default_handlers(val_data,
                                                        event_handlers)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize_handlers(event_handlers)

        for handler in train_begin:
            handler.train_begin(self)

        while not self.stop_training:
            for handler in epoch_begin:
                handler.epoch_begin(self)
            for batch in train_data:
                for handler in batch_begin:
                    handler.batch_begin(self, batch=batch)
                data, label, pred, loss = self.fit_batch(batch, batch_axis)
                bs = data.shape[batch_axis]
                self.trainer.step(bs)
                for handler in batch_end:
                    handler.batch_end(self, batch=batch, pred=pred,
                                      label=label, loss=loss)
                if self.stop_training:
                    break
            for handler in epoch_end:
                handler.epoch_end(self)

        for handler in train_end:
            handler.train_end(self)

    def _prepare_default_handlers(self, val_data, event_handlers):
        event_handlers = list(event_handlers or [])
        added = []
        if not any(isinstance(h, StoppingHandler) for h in event_handlers):
            event_handlers.append(StoppingHandler(self.max_epoch,
                                                  self.max_batch))
        if not any(isinstance(h, MetricHandler) for h in event_handlers):
            event_handlers.append(MetricHandler(self.train_metrics))
            added.append("MetricHandler")
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler)
                        for h in event_handlers):
            event_handlers.append(ValidationHandler(val_data, self.evaluate))
            added.append("ValidationHandler")
        if not any(isinstance(h, LoggingHandler) for h in event_handlers):
            event_handlers.append(LoggingHandler(
                metrics=self.train_metrics + self.val_metrics))
            added.append("LoggingHandler")
        event_handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return event_handlers

    @staticmethod
    def _categorize_handlers(event_handlers):
        train_begin, epoch_begin, batch_begin = [], [], []
        batch_end, epoch_end, train_end = [], [], []
        for handler in event_handlers:
            if isinstance(handler, TrainBegin):
                train_begin.append(handler)
            if isinstance(handler, EpochBegin):
                epoch_begin.append(handler)
            if isinstance(handler, BatchBegin):
                batch_begin.append(handler)
            if isinstance(handler, BatchEnd):
                batch_end.append(handler)
            if isinstance(handler, EpochEnd):
                epoch_end.append(handler)
            if isinstance(handler, TrainEnd):
                train_end.append(handler)
        return (train_begin, epoch_begin, batch_begin, batch_end, epoch_end,
                train_end)
