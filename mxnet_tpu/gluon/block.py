"""Block / HybridBlock / SymbolBlock.

TPU-native re-design of Gluon blocks (ref: python/mxnet/gluon/block.py:178
Block, :765 HybridBlock, :966 hybridize, :859-896 _build_cache→CachedOp,
:1129 SymbolBlock). The CachedOp analog here IS ``jax.jit``: hybridize()
traces ``hybrid_forward`` once per input signature into a single XLA
computation (ref: src/imperative/cached_op.cc:96-822), with:

- cache keyed on input shapes/dtypes + train mode (SetForwardGraph's
  shape-keyed cache, cached_op.cc:307),
- whole-graph backward captured as ONE tape node via jax.vjp
  (CachedOp::Gradient, cached_op.cc:231),
- ``static_alloc`` mapping to XLA buffer donation semantics (no-op knob
  kept for API parity — XLA plans memory statically always),
- BatchNorm-style aux-state updates threaded out of the pure function and
  applied after each call (the reference mutates aux in-place inside the op).
"""
from __future__ import annotations

import re
import threading
import time as _time

import jax
import jax.numpy as jnp

from .. import autograd
from .. import profiler as _profiler
from .. import ndarray as nd
from .. import random as _random
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "make_pure_forward"]


class _BlockScope(threading.local):
    def __init__(self):
        self.counters = {}
        self.prefix = ""     # active name_scope() prefix
        self.stack = []      # per-scope counters (numbering restarts)


_SCOPE = _BlockScope()


def _gen_prefix(hint):
    counters = _SCOPE.stack[-1] if _SCOPE.stack else _SCOPE.counters
    cnt = counters.get(hint, 0)
    counters[hint] = cnt + 1
    return _SCOPE.prefix + "%s%d_" % (hint, cnt)


class _AuxCollector(threading.local):
    """Collects (param, new_data) aux updates produced during a traced
    forward so they can be returned from the pure function."""

    def __init__(self):
        self.stack = []

    def active(self):
        return bool(self.stack)

    def add(self, param, new_data):
        self.stack[-1].append((param, new_data))


_AUX = _AuxCollector()


class Block:
    """Base for all layers/models (ref: gluon/block.py:178)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        if prefix is not None:
            # explicit prefixes nest under an active name_scope, like the
            # reference's _BlockScope.create (ref: gluon/block.py:36)
            self._prefix = (_SCOPE.prefix + prefix) if prefix else prefix
        else:
            self._prefix = _gen_prefix(self._alias())
        self._params = ParameterDict(self._prefix, shared=params)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return type(self).__name__.lower()

    # -- attribute magic: auto-register children & params -----------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
                self._params._params[value.name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    def name_scope(self):
        """Children (and explicit prefixes) created inside the scope nest
        under this block's prefix, and name numbering restarts per scope
        (ref: gluon/block.py Block.name_scope over _BlockScope)."""
        block = self

        class _NS:
            def __enter__(self_ns):
                self_ns._saved_prefix = _SCOPE.prefix
                _SCOPE.prefix = block._prefix
                _SCOPE.stack.append({})
                return block

            def __exit__(self_ns, *a):
                _SCOPE.prefix = self_ns._saved_prefix
                _SCOPE.stack.pop()
                return None
        return _NS()

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """ref: block.py collect_params — regex select supported."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            ret.update({n: p for n, p in self._params.items() if pat.match(n)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def _collect_params_with_prefix(self, prefix=""):
        """Structural parameter paths ("features.0.weight"), stable across
        model instances (ref: block.py _collect_params_with_prefix) — the
        serialization key space for save/load_parameters."""
        out = {}
        for name, p in self._reg_params.items():
            out[prefix + name] = p
        for cname, child in self._children.items():
            out.update(child._collect_params_with_prefix(
                prefix + cname + "."))
        return out

    # -- persistence (ref: block.py:366 save_parameters, :408 load) -------
    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        if deduplicate:
            seen = {}
            arg = {}
            for n, p in params.items():
                if p._data is None:
                    continue
                if id(p) in seen:
                    continue
                seen[id(p)] = n
                arg[n] = p.data()
        else:
            arg = {n: p.data() for n, p in params.items()
                   if p._data is not None}
        nd.save(filename, arg)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        loaded = nd.load(filename)
        canonical = self._collect_params_with_prefix()
        if loaded and canonical and not any(k in canonical for k in loaded):
            # fall back to full-name keys written by older ParameterDict.save
            params = self.collect_params()
            canonical = {}
            for n, p in params.items():
                short = n[len(self._prefix):] \
                    if n.startswith(self._prefix) else n
                canonical[short] = p
        for k, v in loaded.items():
            if k in canonical:
                if cast_dtype and dtype_source == "saved":
                    # adopt the checkpoint's dtype (ref: block.py:408
                    # load_parameters cast_dtype semantics)
                    canonical[k].cast(str(v.dtype))
                canonical[k].set_data(v)
            elif not ignore_extra:
                raise KeyError("Parameter %r in file not found in Block" % k)
        if not allow_missing:
            missing = [k for k, p in canonical.items()
                       if p._data is None and p._deferred_init is None
                       and k not in loaded]
            if missing:
                raise KeyError("Missing parameters in file: %s" % missing)

    save_params = save_parameters
    load_params = load_parameters

    # -- call path --------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        out = self(*inputs)
        lines = ["%s: %d parameters" % (self.name, sum(
            int(p.data().size) for p in self.collect_params().values()
            if p._data is not None))]
        return "\n".join(lines)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def __repr__(self):
        s = "%s(\n" % type(self).__name__
        for key, child in self._children.items():
            s += "  (%s): %s\n" % (key, repr(child).replace("\n", "\n  "))
        return s + ")"


class HybridBlock(Block):
    """Block that can be traced to one XLA computation (ref: block.py:765)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graph = {}
        self._static_alloc = False
        self._static_shape = False

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=None, forward_bulk_size=None,
                  backward_bulk_size=None):
        """ref: block.py:966. static_alloc/static_shape accepted for parity;
        XLA always plans memory statically."""
        self._active = active
        self._static_alloc = static_alloc
        self._static_shape = static_shape
        self._cached_graph = {}
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape)

    def infer_shape(self, *args):
        self._deferred_infer_shape(*args)

    def _deferred_infer_shape(self, *args):
        """Run an abstract (shape-only) forward to finish deferred param
        init — the analog of the reference's shape-inference pass before
        CachedOp creation (ref: block.py _deferred_infer_shape)."""
        try:
            with autograd.pause():
                jax.eval_shape(self._abstract_forward,
                               *[jax.ShapeDtypeStruct(a.shape, a.dtype)
                                 for a in args])
        except DeferredInitializationError:
            raise
        except Exception:
            # fall back: eager forward on zeros would also trigger init;
            # abstract pass can fail when params are entirely uninitialized
            raise

    def _abstract_forward(self, *datas):
        outs = self.forward(*[NDArray(d) for d in datas])
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        return tuple(o._data for o in outs)

    def cast(self, dtype):
        super().cast(dtype)
        self._cached_graph = {}

    # -- forward ----------------------------------------------------------
    def __call__(self, *args):
        if self._active:
            return self._call_cached_op(*args)
        return super().__call__(*args)

    def forward(self, x, *args):
        """Eager path: pass NDArrays + param NDArrays to hybrid_forward
        (ref: block.py:1054 HybridBlock.forward). Symbol inputs switch F
        to the symbol namespace and bind params as named variables — the
        reference's symbolic tracing path (``net(mx.sym.var('data'))``),
        which is what ONNX export and Module bind consume."""
        from ..symbol import Symbol as _Sym
        if isinstance(x, _Sym):
            from .. import symbol as _sym_mod
            params = {name: _sym_mod.var(p.name)
                      for name, p in self._reg_params.items()}
            return self.hybrid_forward(_sym_mod, x, *args, **params)
        params = {}
        for name, p in self._reg_params.items():
            try:
                params[name] = p.data()
            except DeferredInitializationError:
                self._infer_param_shapes(x, *args)
                params[name] = p.data()
        return self.hybrid_forward(nd, x, *args, **params)

    def _infer_param_shapes(self, *args):
        """Finish deferred init by running shape inference via eval_shape of
        hybrid_forward with zero-filled placeholder params."""
        hinted = self._shape_hint(*args)
        for p in self._reg_params.values():
            if p._data is None and p._deferred_init is not None:
                shape = hinted.get(p)
                if shape is None:
                    raise DeferredInitializationError(
                        "cannot infer shape for %s" % p.name)
                p._finish_deferred_init(shape)

    def _shape_hint(self, *args):
        """Subclasses (Dense/Conv/...) override to map input shapes to param
        shapes for deferred init."""
        return {}

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- CachedOp analog ---------------------------------------------------
    def _call_cached_op(self, *args):
        nd_args = [a for a in args if isinstance(a, NDArray)]
        # finish deferred init first (eager trace of shapes)
        for p in self._all_params_list():
            if p._data is None and p._deferred_init is not None:
                with autograd.pause():
                    Block.__call__(self, *args)  # eager forward initializes
                break
        params = self._all_params_list()
        param_datas = [p.data()._data for p in params]
        training = autograd.is_training()
        from ..ndarray import register as _op_register
        sig = (tuple((a.shape, str(a.dtype)) for a in nd_args), training,
               _op_register._amp_version)
        entry = self._cached_graph.get(sig)
        # fresh signature: time trace + XLA compile + first run into the
        # compile-attribution registry (the _compile_probe convention —
        # hybridized forward compiles were invisible to the registry and
        # hence to the hlolint/roofline joins before ISSUE 18)
        c0 = _time.perf_counter() if entry is None else None
        if entry is None:
            entry = self._build_cached_graph(params, training)
            self._cached_graph[sig] = entry
        jitted, n_outs, aux_params = entry

        rng = _random.next_key()
        in_datas = tuple(a._data for a in nd_args)

        if autograd.is_recording():
            def run(pd, xd):
                return jitted(pd, xd, rng)
            (out_datas, aux_datas), vjp_fn = jax.vjp(
                run, tuple(param_datas), in_datas)

            def vjp_flat(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                zero_aux = tuple(jnp.zeros(a.shape, a.dtype)
                                 for a in aux_datas)
                pd_cts, xd_cts = vjp_fn((tuple(cts), zero_aux))
                return tuple(pd_cts) + tuple(xd_cts)

            out_nds = [NDArray(o) for o in out_datas]
            inputs = [p.data() for p in params] + nd_args
            node = autograd.record_op(
                "CachedOp(%s)" % self.name, out_nds, inputs, vjp_flat)
            node.fwd_fn = None  # create_graph through cached op unsupported
        else:
            out_datas, aux_datas = jitted(tuple(param_datas), in_datas, rng)
            out_nds = [NDArray(o) for o in out_datas]

        if c0 is not None:
            _profiler.record_compile(
                "cached_graph:%s" % (self.name or type(self).__name__),
                key="%d inputs, training=%s"
                    % (len(nd_args), training),
                dur_us=(_time.perf_counter() - c0) * 1e6)

        # apply aux updates (moving stats)
        for p, new in zip(aux_params, aux_datas):
            p.data()._data = new
        return out_nds[0] if len(out_nds) == 1 else tuple(out_nds)

    def _all_params_list(self):
        seen, out = set(), []
        for _, p in sorted(self._collect_params_with_prefix().items()):
            if id(p) not in seen:
                seen.add(id(p))
                out.append(p)
        return out

    def _build_cached_graph(self, params, training):
        """Trace the block's forward into one jitted pure function.
        Analog of CachedOp::SetForwardGraph + StaticInitExec
        (ref: src/imperative/cached_op.cc:307,584)."""
        def call(*input_nds):
            return Block.__call__(self, *input_nds)

        pure_fn, aux_params = make_pure_forward(params, call, training)
        jitted = jax.jit(pure_fn)
        # trigger nothing yet; n_outs resolved on first call via structure
        return jitted, None, aux_params

    def export(self, path, epoch=0):
        """Serialize architecture + params for deployment
        (ref: block.py:1004 export)."""
        params = self.collect_params()
        arg = {("arg:%s" % n): p.data() for n, p in params.items()
               if p._data is not None}
        nd.save("%s-%04d.params" % (path, epoch), arg)
        import json
        graph = {"framework": "mxnet_tpu", "block": type(self).__name__,
                 "params": sorted(params.keys())}
        with open("%s-symbol.json" % path, "w") as f:
            json.dump(graph, f, indent=2)

    # optimization barrier for API parity
    def optimize_for(self, x, backend=None, **kwargs):
        self.hybridize(True)
        return self(x)


def make_pure_forward(params, call, training):
    """Build the pure-functional form of an eager forward: returns
    ``(pure_fn, aux_params)`` where ``pure_fn(param_datas, input_datas,
    rng_key) -> (out_datas, aux_datas)`` runs ``call`` with the traced
    param buffers swapped into ``params``, recording off, train mode set,
    and the PRNG stream keyed off ``rng_key``. The CachedOp purification
    seam shared by HybridBlock._build_cached_graph and the gluon fused
    train step (gluon/fused_step.py).

    Aux-state updates (BatchNorm moving stats) are threaded out of the
    pure function two ways: ``report_aux_update`` collection (eager
    stateful layers) and direct ``p.data()._data`` rebinds (a hybridized
    child applying its own cached-op aux inside this trace — previously
    those were silently dropped by the originals restore). ``aux_params``
    is repopulated on every trace, ordered like ``aux_datas``."""
    aux_params = []

    def pure_fn(param_datas, input_datas, rng_key):
        # swap traced data into the parameters, run eager forward
        originals = [p.data()._data for p in params]
        for p, d in zip(params, param_datas):
            p.data()._data = d
        _random.push_trace_key(rng_key)
        collected = []
        _AUX.stack.append(collected)
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(training)
        mutated = []
        try:
            out = call(*[NDArray(d) for d in input_datas])
        finally:
            autograd.set_training(prev_train)
            autograd.set_recording(prev_rec)
            _AUX.stack.pop()
            _random.pop_trace_key()
            for p, d, orig in zip(params, param_datas, originals):
                cur = p.data()._data
                if cur is not d and cur is not orig:
                    mutated.append((p, cur))
            for p, d in zip(params, originals):
                p.data()._data = d
        outs = out if isinstance(out, (tuple, list)) else (out,)
        aux_params.clear()
        aux_datas = []
        for p, new_data in collected + mutated:
            aux_params.append(p)
            aux_datas.append(new_data)
        return tuple(o._data for o in outs), tuple(aux_datas)

    return pure_fn, aux_params


def report_aux_update(param, new_data):
    """Called by stateful layers (BatchNorm) to publish running-stat updates.
    Under a cached-op trace the update is collected and threaded out of the
    pure function; eagerly it is applied immediately."""
    if _AUX.active():
        _AUX.add(param, new_data)
    else:
        param.data()._data = new_data


class SymbolBlock(HybridBlock):
    """Wrap a Symbol graph as a block (ref: block.py:1129). Takes a Symbol
    and input symbols; parameters come from the symbol's arguments."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol import Symbol
        self._outputs = outputs if isinstance(outputs, Symbol) else outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        input_names = {s.name for s in self._inputs}
        for argname in self._outputs.list_arguments():
            if argname not in input_names:
                p = Parameter(argname, allow_deferred_init=True)
                self._params._params[argname] = p
                self._reg_params[argname] = p
        for auxname in self._outputs.list_auxiliary_states():
            if auxname not in input_names:
                p = Parameter(auxname, grad_req="null",
                              allow_deferred_init=True)
                self._params._params[auxname] = p
                self._reg_params[auxname] = p

    @classmethod
    def imports(cls, symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load, var as sym_var
        sym = sym_load(symbol_file)
        inputs = [sym_var(n) for n in (input_names if isinstance(
            input_names, (list, tuple)) else [input_names])]
        ret = cls(sym, inputs)
        if param_file:
            loaded = nd.load(param_file)
            cleaned = {}
            for k, v in loaded.items():
                cleaned[k.split(":", 1)[-1]] = v
            for name, p in ret._params.items():
                if name in cleaned:
                    p.set_data(cleaned[name])
        return ret

    def forward(self, *args):
        """Run the wrapped graph as ONE recorded op: forward interprets the
        graph into jax (tracing into any active jit), and when autograd is
        recording the whole graph joins the tape via jax.vjp — the same
        contract as a generated op (ref: block.py:1129 SymbolBlock runs a
        CachedOp)."""
        import jax
        from .. import autograd as _ag
        from .. import random as _random
        from ..executor import _GraphProgram

        prog = getattr(self, "_prog", None)
        if prog is None:
            prog = self._prog = _GraphProgram(self._outputs)
        names = [s.name for s in self._inputs]
        nd_args = [a if isinstance(a, NDArray) else nd.array(a)
                   for a in args]
        # finish deferred param init from the graph's shape inference
        if any(p._data is None for p in self._reg_params.values()):
            shapes = {s.name: tuple(a.shape)
                      for s, a in zip(self._inputs, nd_args)}
            arg_shapes, _, aux_shapes = \
                self._outputs.infer_shape_partial(**shapes)
            arg_names = self._outputs.list_arguments()
            aux_names = self._outputs.list_auxiliary_states()
            for n, s in list(zip(arg_names, arg_shapes)) + \
                    list(zip(aux_names, aux_shapes)):
                p = self._reg_params.get(n)
                if p is not None and p._data is None and s is not None:
                    p._finish_deferred_init(tuple(s))
        param_items = list(self._reg_params.items())
        all_names = names + [n for n, _ in param_items]
        nd_inputs = nd_args + [p.data() for _, p in param_items]
        key = _random.next_key()
        training = _ag.is_training()

        datas = tuple(a._data for a in nd_inputs)
        # aux (BatchNorm moving stats) come back as EXTRA outputs so their
        # values survive jax.vjp tracing; probe the key set abstractly
        aux_keys = []
        if training:
            def probe(*d):
                return prog.run(dict(zip(all_names, d)), True, key)[1]
            try:
                aux_keys = sorted(jax.eval_shape(
                    probe, *[jax.ShapeDtypeStruct(a.shape, a.dtype)
                             for a in datas]))
            except Exception:
                aux_keys = []

        def fwd(*datas):
            values = dict(zip(all_names, datas))
            outs, aux_up = prog.run(values, training, key)
            return tuple(outs) + tuple(
                jax.lax.stop_gradient(aux_up[k]) for k in aux_keys)

        if _ag.is_recording():
            out, vjp_fn = jax.vjp(fwd, *datas)
            all_outs = [NDArray(o) for o in out]

            def vjp_wrap(cts):
                # the tape hands a bare cotangent for single-output nodes;
                # fwd always returns a tuple
                return vjp_fn(cts if isinstance(cts, tuple) else (cts,))

            _ag.record_op("SymbolBlock", all_outs, nd_inputs, vjp_wrap)
        else:
            all_outs = [NDArray(o) for o in fwd(*datas)]
        n_real = len(all_outs) - len(aux_keys)
        outs = all_outs[:n_real]
        # deliver the moving-stat writes to the registered aux params
        # (ref: the reference's stateful BatchNorm mutating aux NDArrays)
        for name, val in zip(aux_keys, all_outs[n_real:]):
            p = self._reg_params.get(name)
            if p is not None and p._data is not None:
                p._data._data = val._data.astype(p._data._data.dtype)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def hybrid_forward(self, F, *args, **kwargs):
        raise RuntimeError("SymbolBlock uses forward directly")
