"""mx.npx — operators and utilities beyond the NumPy standard
(ref: python/mxnet/numpy_extension/__init__.py; op kernels in
src/operator/numpy/). Bridges the deep-learning op registry (Activation,
BatchNorm, Convolution, …) into the np-array world: inputs/outputs are
``mx.np.ndarray`` and everything records on the autograd tape."""
from __future__ import annotations

import jax.numpy as jnp

from .. import util
from ..util import (set_np, reset_np, set_np_shape, is_np_shape,
                    is_np_array, use_np, use_np_shape, use_np_array,
                    np_shape, np_array)  # noqa: F401
from ..context import cpu, gpu, tpu, num_gpus, num_tpus, \
    current_context  # noqa: F401
from .. import random as _random
from ..ndarray import register as _register
from ..ndarray.ndarray import NDArray
from ..numpy.multiarray import ndarray, _np_invoke

__all__ = ["set_np", "reset_np", "set_np_shape", "is_np_shape",
           "is_np_array", "use_np", "use_np_shape", "use_np_array",
           "np_shape", "np_array", "cpu", "gpu", "tpu", "num_gpus",
           "num_tpus", "current_context", "seed", "waitall", "load",
           "save", "reshape_like", "arange_like"]


def seed(s):
    _random.seed(s)


def waitall():
    from .. import ndarray as nd
    nd.waitall()


def save(file, arr):
    """Save np arrays (dict/list/single) (ref: npx save → MXNDArraySave)."""
    from ..ndarray import save as _save
    _save(file, arr)


def load(file):
    from ..ndarray import load as _load
    out = _load(file)
    if isinstance(out, dict):
        return {k: ndarray._adopt(v) for k, v in out.items()}
    if isinstance(out, list):
        return [ndarray._adopt(v) for v in out]
    return ndarray._adopt(out)


def reshape_like(lhs, rhs):
    """Reshape lhs to rhs's shape (ref: src/operator/tensor/
    elemwise_unary_op_basic.cc reshape_like)."""
    return _np_invoke(lambda a, b: jnp.reshape(a, b.shape), (lhs, rhs), {},
                      op_name="reshape_like")


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """ref: src/operator/tensor/init_op.cc _npx_arange_like."""
    def fn(x):
        if axis is None:
            n = x.size
            out = start + step * jnp.arange(n, dtype=x.dtype)
            return out.reshape(x.shape)
        n = x.shape[axis]
        return start + step * jnp.arange(n, dtype=x.dtype)
    return _np_invoke(fn, (data,), {}, op_name="arange_like")


# -- registry-op bridge ------------------------------------------------------
# npx exposes the nn op surface with np-array outputs; same kernels as mx.nd
# (ref: python/mxnet/ndarray/numpy_extension/ generated wrappers)
_NPX_OPS = [
    "Activation", "BatchNorm", "Convolution", "Deconvolution", "Pooling",
    "FullyConnected", "Dropout", "Embedding", "LayerNorm", "GroupNorm",
    "InstanceNorm", "L2Normalization", "LeakyReLU", "RNN", "softmax",
    "log_softmax", "masked_softmax", "topk", "pick", "one_hot", "batch_dot",
    "gather_nd", "scatter_nd", "relu", "sigmoid", "smooth_l1",
    "sequence_mask", "broadcast_like", "SequenceMask", "SequenceLast",
    "SequenceReverse", "shape_array", "stop_gradient",
]


def _np_op_wrapper(name):
    try:
        from ..ops.registry import get_op
        opdef = get_op(name)
    except KeyError:
        return None

    def fn(*args, **kwargs):
        out = _register.invoke(opdef, args, kwargs)
        if isinstance(out, tuple):
            return tuple(ndarray._adopt(o) if isinstance(o, NDArray) else o
                         for o in out)
        return ndarray._adopt(out) if isinstance(out, NDArray) else out
    fn.__name__ = name
    fn.__doc__ = "mx.npx.%s — registry op with np-array outputs " \
        "(ref: python/mxnet/ndarray/numpy_extension/)" % name
    return fn


import re as _re

# names whose mechanical camel→snake split is wrong (acronym runs)
_SNAKE_SPECIAL = {"LeakyReLU": "leaky_relu", "RNN": "rnn",
                  "L2Normalization": "l2_normalization"}


def _snake(name):
    special = _SNAKE_SPECIAL.get(name)
    if special is not None:
        return special
    return _re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name).lower()


for _name in _NPX_OPS:
    _fn = _np_op_wrapper(_name)
    if _fn is not None:
        globals()[_name] = _fn
        # npx uses snake_case names for nn ops (npx.fully_connected etc.,
        # ref: python/mxnet/ndarray/numpy_extension/_op.py)
        lower = _snake(_name)
        if lower not in globals():
            globals()[lower] = _fn
        __all__.append(_name)


def gamma(x, out=None, **kwargs):
    """Gamma function (ref: npx special functions over
    src/operator/mshadow_op.h gamma; exp(gammaln) with the reflection
    formula for the negative axis)."""
    import jax
    import jax.numpy as jnp
    from ..numpy.multiarray import _wrap_out
    from ..ndarray import NDArray
    d = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    pos = jnp.exp(jax.scipy.special.gammaln(d))
    # reflection: Gamma(x) = pi / (sin(pi x) * Gamma(1 - x)) for x < 0
    neg = jnp.pi / (jnp.sin(jnp.pi * d)
                    * jnp.exp(jax.scipy.special.gammaln(1.0 - d)))
    return _wrap_out(jnp.where(d > 0, pos, neg))


__all__.append("gamma")
