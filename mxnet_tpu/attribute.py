"""Attribute scoping for symbols (ref: python/mxnet/attribute.py
AttrScope): symbols created inside the scope inherit its attributes —
the mechanism the reference uses for `group2ctx` model-parallel context
groups (`with mx.AttrScope(ctx_group='dev1'):`) and custom node tags."""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_current = threading.local()


def _stack():
    if not hasattr(_current, "stack"):
        _current.stack = []
    return _current.stack


class AttrScope:
    """ref: attribute.py:26 AttrScope."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("Attributes need to be strings")
        self._attr = kwargs

    def get(self, attr=None):
        """Merge scope attrs over `attr` (ref: attribute.py get)."""
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *args):
        _stack().pop()


def current():
    """Merged attributes of all active scopes (outermost first)."""
    merged = {}
    for scope in _stack():
        merged.update(scope._attr)
    return merged


def apply(attrs):
    """Scope attrs with `attrs` layered on top (explicit wins) — the one
    place node builders merge AttrScope state (ref: attribute.py get)."""
    merged = current()
    if attrs:
        merged.update(attrs)
    return merged
