"""TensorInspector: interactive/value-check debugging for tensors
(ref: src/common/tensor_inspector.h — print_string, check for NaN/inf,
value dumping with visit-count tagging).

The reference's C++ class is constructed around a TBlob inside kernels;
here the same checks work on any NDArray / jax array / numpy array from
Python, which is where TPU debugging happens. Two device-friendly paths
(ISSUE 15 satellite):

- :meth:`TensorInspector.snapshot` inspects MANY tensors with ONE
  batched ``jax.device_get`` transfer — inspecting a whole parameter
  dict no longer round-trips the device once per tensor.
- :meth:`TensorInspector.print_in_trace` /
  :meth:`TensorInspector.check_in_trace` are ``jax.debug.print``-based
  variants usable INSIDE jitted code, where host-side numpy conversion
  is impossible — they print shape/dtype plus nonfinite/abs-max/L2 at
  run time and return the operand unchanged, so they drop into any
  traced expression."""
from __future__ import annotations

import logging

import numpy as _np

__all__ = ["TensorInspector"]


def _to_host(tensor):
    """One host copy of ``tensor`` (NDArray unwrapped first): device
    arrays go through ``jax.device_get``, host values through
    ``np.asarray``."""
    from .ndarray.ndarray import NDArray
    if isinstance(tensor, NDArray):
        tensor = tensor._data
    if isinstance(tensor, _np.ndarray):
        return tensor
    if hasattr(tensor, "sharding") or hasattr(tensor, "devices"):
        import jax
        return _np.asarray(jax.device_get(tensor))
    return _np.asarray(tensor)


class TensorInspector:
    """ref: tensor_inspector.h TensorInspector(tb, ctx)."""

    _visit_count = {}

    def __init__(self, tensor, tag=""):
        self._a = _to_host(tensor)
        self.tag = tag

    @classmethod
    def snapshot(cls, tensors, tags=None):
        """Build inspectors for many tensors with ONE batched host
        transfer (``jax.device_get`` over the whole list — the per-call
        numpy round-trip was the ISSUE 15 satellite complaint).

        ``tensors``: an iterable of NDArray/jax/numpy values, or a
        ``{name: tensor}`` dict (names become the tags). ``tags``
        optionally labels list input. Returns a list (or dict, matching
        the input shape) of :class:`TensorInspector`."""
        from .ndarray.ndarray import NDArray
        if isinstance(tensors, dict):
            names = list(tensors)
            vals = [tensors[k] for k in names]
        else:
            names = list(tags) if tags is not None else None
            vals = list(tensors)
        datas = [t._data if isinstance(t, NDArray) else t for t in vals]
        import jax
        hosts = jax.device_get(datas)
        out = [cls(_np.asarray(h),
                   tag=(names[i] if names is not None else ""))
               for i, h in enumerate(hosts)]
        if isinstance(tensors, dict):
            return dict(zip(names, out))
        return out

    def print_string(self):
        """Formatted dump with shape/dtype header (ref: print_string())."""
        return "<%s %s %s>\n%s" % (self.tag or "Tensor",
                                   "x".join(map(str, self._a.shape)),
                                   self._a.dtype,
                                   _np.array2string(self._a, threshold=64))

    def check_value(self, checker=None):
        """Return coordinates of values failing the check; default checker
        flags NaN/Inf (ref: check_value w/ CheckerType::NegativeChecker
        etc. — pass any predicate)."""
        if checker is None:
            def checker(x):
                return ~_np.isfinite(x)
        mask = checker(self._a)
        coords = [tuple(int(i) for i in idx)
                  for idx in _np.argwhere(mask)]
        if coords:
            logging.warning("TensorInspector%s: %d values failed the check "
                            "(first at %s)",
                            " [%s]" % self.tag if self.tag else "",
                            len(coords), coords[0])
        return coords

    def has_nan_or_inf(self):
        return not bool(_np.isfinite(self._a).all())

    def dump_to_file(self, tag, visit=True):
        """Save to '<tag>_<visit>.npy' with a visit counter so repeated
        passes don't overwrite (ref: dump_to_file visit-count naming)."""
        count = TensorInspector._visit_count.get(tag, 0) + 1
        if visit:
            TensorInspector._visit_count[tag] = count
        fname = "%s_%d.npy" % (tag, count)
        _np.save(fname, self._a)
        return fname

    # -- in-trace variants (usable inside jitted code) -----------------------

    @staticmethod
    def print_in_trace(x, tag=""):
        """``jax.debug.print``-based inspector usable INSIDE jitted
        code: prints ``<tag shape dtype> nonfinite/absmax/l2`` at RUN
        time (shape/dtype are trace-static and land in the format
        string; the stats are traced values) and returns ``x``
        unchanged, so it drops into any traced expression::

            y = TensorInspector.print_in_trace(y, tag="logits")
        """
        import jax
        import jax.numpy as jnp
        hdr = ("TensorInspector[%s] <%s %s>" % (
            tag or "Tensor", "x".join(map(str, x.shape)), x.dtype)
        ).replace("{", "{{").replace("}", "}}")  # tag-safe fmt string
        if jnp.issubdtype(x.dtype, jnp.floating) or \
                jnp.issubdtype(x.dtype, jnp.complexfloating):
            x32 = jnp.abs(x).astype(jnp.float32)
            jax.debug.print(
                hdr + " nonfinite={bad} absmax={amax} l2={l2}",
                bad=jnp.sum((~jnp.isfinite(x)).astype(jnp.int32)),
                amax=jnp.max(x32) if x.size else jnp.float32(0),
                l2=jnp.sqrt(jnp.sum(x32 * x32)))
        else:
            jax.debug.print(hdr + " min={mn} max={mx}",
                            mn=jnp.min(x) if x.size else 0,
                            mx=jnp.max(x) if x.size else 0)
        return x

    @staticmethod
    def check_in_trace(x, tag=""):
        """In-trace NaN/inf check: prints a warning line (via
        ``jax.debug.print``) carrying the nonfinite count — 0 on a
        clean tensor — and returns ``x`` unchanged. The in-jit sibling
        of :meth:`check_value` for code that cannot leave the trace."""
        import jax
        import jax.numpy as jnp
        bad = jnp.sum((~jnp.isfinite(x)).astype(jnp.int32)) \
            if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.int32(0)
        hdr = ("TensorInspector[%s] check:" % (tag or "Tensor")) \
            .replace("{", "{{").replace("}", "}}")
        jax.debug.print(hdr + " nonfinite={bad}", bad=bad)
        return x
