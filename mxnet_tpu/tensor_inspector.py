"""TensorInspector: interactive/value-check debugging for tensors
(ref: src/common/tensor_inspector.h — print_string, check for NaN/inf,
value dumping with visit-count tagging).

The reference's C++ class is constructed around a TBlob inside kernels;
here the same checks work on any NDArray / jax array / numpy array from
Python, which is where TPU debugging happens (device-side printing goes
through jax.debug.print instead)."""
from __future__ import annotations

import logging

import numpy as _np

__all__ = ["TensorInspector"]


class TensorInspector:
    """ref: tensor_inspector.h TensorInspector(tb, ctx)."""

    _visit_count = {}

    def __init__(self, tensor, tag=""):
        from .ndarray.ndarray import NDArray
        if isinstance(tensor, NDArray):
            self._a = tensor.asnumpy()
        else:
            self._a = _np.asarray(tensor)
        self.tag = tag

    def print_string(self):
        """Formatted dump with shape/dtype header (ref: print_string())."""
        return "<%s %s %s>\n%s" % (self.tag or "Tensor",
                                   "x".join(map(str, self._a.shape)),
                                   self._a.dtype,
                                   _np.array2string(self._a, threshold=64))

    def check_value(self, checker=None):
        """Return coordinates of values failing the check; default checker
        flags NaN/Inf (ref: check_value w/ CheckerType::NegativeChecker
        etc. — pass any predicate)."""
        if checker is None:
            def checker(x):
                return ~_np.isfinite(x)
        mask = checker(self._a)
        coords = [tuple(int(i) for i in idx)
                  for idx in _np.argwhere(mask)]
        if coords:
            logging.warning("TensorInspector%s: %d values failed the check "
                            "(first at %s)",
                            " [%s]" % self.tag if self.tag else "",
                            len(coords), coords[0])
        return coords

    def has_nan_or_inf(self):
        return not bool(_np.isfinite(self._a).all())

    def dump_to_file(self, tag, visit=True):
        """Save to '<tag>_<visit>.npy' with a visit counter so repeated
        passes don't overwrite (ref: dump_to_file visit-count naming)."""
        count = TensorInspector._visit_count.get(tag, 0) + 1
        if visit:
            TensorInspector._visit_count[tag] = count
        fname = "%s_%d.npy" % (tag, count)
        _np.save(fname, self._a)
        return fname
