#!/usr/bin/env python
"""PTB-style LSTM language model on the legacy symbolic RNN API
(ref: example/rnn/bucketing/lstm_bucketing.py): BucketSentenceIter +
mx.rnn.SequentialRNNCell/LSTMCell + BucketingModule.fit, with
save/load via the rnn checkpoint helpers.

Runs self-contained on a synthetic corpus by default (zero-egress CI);
pass --train FILE with one sentence per line for real data.

    python example/rnn/lstm_bucketing.py --epochs 2

TPU note: each bucket length compiles once (one XLA program per bucket
via the BucketingModule's shared-module bind), so keep the bucket list
short — the reference's [10, 20, 30, 40, 50, 60] default works.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def synthetic_corpus(n=400, seed=0):
    """Markov-ish token stream so the LM has learnable structure."""
    rs = np.random.RandomState(seed)
    words = ["the", "a", "cat", "dog", "sat", "ran", "on", "mat", "log",
             "fast", "slow", "big", "small", "and", "then"]
    sents = []
    for _ in range(n):
        ln = rs.randint(4, 12)
        sents.append([words[rs.randint(len(words))] for _ in range(ln)])
    return [" ".join(s) for s in sents]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train", default=None, help="one sentence per line")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=200)
    ap.add_argument("--num-embed", type=int, default=200)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--buckets", default="10,20,30,40,50,60")
    ap.add_argument("--save-prefix", default=None)
    args = ap.parse_args()

    if args.train:
        with open(args.train) as f:
            lines = [ln.split() for ln in f if ln.strip()]
    else:
        lines = [ln.split() for ln in synthetic_corpus()]

    sentences, vocab = mx.rnn.encode_sentences(lines, invalid_label=0,
                                               start_label=1)
    vocab_size = max(vocab.values()) + 1
    buckets = [int(b) for b in args.buckets.split(",")]
    buckets = [b for b in buckets
               if any(len(s) <= b for s in sentences)]
    data_train = mx.rnn.BucketSentenceIter(
        sentences, args.batch_size, buckets=buckets, invalid_label=0)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label,
                                    name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=data_train.default_bucket_key)

    metric = mx.metric.Perplexity(ignore_label=None)
    model.bind(data_shapes=data_train.provide_data,
               label_shapes=data_train.provide_label)
    model.init_params(initializer=mx.init.Xavier(factor_type="in",
                                                 magnitude=2.34))
    model.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": args.lr,
                                           "momentum": 0.9})
    for epoch in range(args.epochs):
        data_train.reset()
        metric.reset()
        for i, batch in enumerate(data_train):
            model.forward(batch, is_train=True)
            model.update_metric(metric, batch.label)
            model.backward()
            model.update()
        print("epoch %d: train %s=%.3f" % (epoch, *metric.get()))
        if args.save_prefix:
            arg, aux = model.get_params()
            sym = sym_gen(data_train.default_bucket_key)[0]
            mx.rnn.save_rnn_checkpoint(stack, args.save_prefix, epoch + 1,
                                       sym, arg, aux)
            print("saved %s-%04d.params" % (args.save_prefix, epoch + 1))


if __name__ == "__main__":
    main()
