#!/usr/bin/env python
"""Character-level LSTM language model with Gluon RNN layers
(ref: example/rnn/ char-rnn examples; example/gluon/word_language_model).

Trains on a small synthetic corpus by default so the script runs
self-contained; pass --text FILE for real data.

    python example/rnn/char_lstm.py --epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, autograd, nd  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402

DEFAULT_TEXT = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 40


class CharLSTM(gluon.Block):
    def __init__(self, vocab, embed=32, hidden=128, layers=2, **kw):
        super().__init__(**kw)
        self.embed = nn.Embedding(vocab, embed)
        self.lstm = rnn.LSTM(hidden, num_layers=layers, layout="NTC")
        self.head = nn.Dense(vocab, flatten=False)

    def forward(self, x, states):
        h = self.embed(x)
        out, states = self.lstm(h, states)
        return self.head(out), states

    def begin_state(self, batch_size):
        return self.lstm.begin_state(batch_size=batch_size)


def batches(text, vocab, batch_size, seq_len):
    data = np.array([vocab[c] for c in text], "int32")
    n = (len(data) - 1) // (batch_size * seq_len)
    x = data[:n * batch_size * seq_len].reshape(batch_size, n, seq_len)
    y = data[1:n * batch_size * seq_len + 1].reshape(batch_size, n, seq_len)
    for i in range(n):
        yield nd.array(x[:, i].astype("float32")), \
            nd.array(y[:, i].astype("float32"))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--text", default=None)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.005)
    args = p.parse_args()

    text = open(args.text).read() if args.text else DEFAULT_TEXT
    chars = sorted(set(text))
    vocab = {c: i for i, c in enumerate(chars)}
    print("corpus %d chars, vocab %d" % (len(text), len(chars)))

    net = CharLSTM(len(chars))
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total, count = 0.0, 0
        states = net.begin_state(args.batch_size)
        for x, y in batches(text, vocab, args.batch_size, args.seq_len):
            # detach state between truncated-BPTT segments
            states = [s.detach() for s in states]
            with autograd.record():
                logits, states = net(x, states)
                L = loss_fn(logits.reshape((-1, len(chars))),
                            y.reshape((-1,)))
            L.backward()
            trainer.step(x.shape[0] * x.shape[1])
            total += float(L.mean().asscalar())
            count += 1
        print("epoch %d: ce %.4f (ppl %.1f)"
              % (epoch, total / count, np.exp(total / count)))

    # sample a few characters greedily
    states = net.begin_state(1)
    idx = vocab["t"]
    out = ["t"]
    for _ in range(60):
        logits, states = net(nd.array([[float(idx)]]), states)
        idx = int(np.argmax(logits.asnumpy()[0, -1]))
        out.append(chars[idx])
    print("sample:", "".join(out))


if __name__ == "__main__":
    main()
