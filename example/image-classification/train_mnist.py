#!/usr/bin/env python
"""Train MNIST with the symbolic Module API
(ref: example/image-classification/train_mnist.py — same script shape:
build a symbol, create the iterators, call fit).

    python example/image-classification/train_mnist.py --network mlp
    python example/image-classification/train_mnist.py --network lenet --tpus 0
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402


def get_mlp():
    """ref: example/image-classification/symbols/mlp.py."""
    data = sym.Variable("data")
    data = sym.Flatten(data)
    fc1 = sym.FullyConnected(data, num_hidden=128, name="fc1")
    act1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act1, num_hidden=64, name="fc2")
    act2 = sym.Activation(fc2, act_type="relu", name="relu2")
    fc3 = sym.FullyConnected(act2, num_hidden=10, name="fc3")
    return sym.SoftmaxOutput(fc3, sym.Variable("softmax_label"),
                             name="softmax")


def get_lenet():
    """ref: example/image-classification/symbols/lenet.py."""
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = sym.Flatten(p2)
    fc1 = sym.Activation(sym.FullyConnected(f, num_hidden=500, name="fc1"),
                         act_type="tanh")
    fc2 = sym.FullyConnected(fc1, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"),
                             name="softmax")


def get_iters(batch_size, flat):
    """MNIST via gluon datasets (synthetic fallback when offline —
    MXTPU_SYNTHETIC_DATA=1); returns NDArrayIter pairs like the
    reference's get_mnist_iter. Reads the dataset's backing numpy arrays
    in one vectorized conversion — per-sample __getitem__ would round-trip
    every row through a device array."""
    from mxnet_tpu.gluon.data.vision import MNIST
    shape = (-1, 784) if flat else (-1, 1, 28, 28)

    def to_iter(ds, shuffle):
        X = np.asarray(ds._data).reshape(shape).astype("float32") / 255.0
        y = np.asarray(ds._label, "float32")
        return mx.io.NDArrayIter(X, y, batch_size=batch_size,
                                 shuffle=shuffle)

    return to_iter(MNIST(train=True), True), to_iter(MNIST(train=False),
                                                     False)


def main():
    parser = argparse.ArgumentParser(
        description="train mnist (ref: train_mnist.py)")
    parser.add_argument("--network", default="mlp",
                        choices=["mlp", "lenet"])
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--tpus", default=None,
                        help="tpu device ids, e.g. '0' (default: cpu; "
                             "ref --gpus)")
    parser.add_argument("--kv-store", default="local")
    args = parser.parse_args()

    net = get_mlp() if args.network == "mlp" else get_lenet()
    ctx = [mx.tpu(int(i)) for i in args.tpus.split(",")] if args.tpus \
        else mx.cpu()
    train, val = get_iters(args.batch_size, flat=args.network == "mlp")

    mod = mx.mod.Module(net, context=ctx)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(magnitude=2.0),
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       100))
    acc = dict(mod.score(val, "acc"))["accuracy"]
    print("final validation accuracy: %.4f" % acc)


if __name__ == "__main__":
    main()
