#!/usr/bin/env python
"""Model-parallel matrix factorization: giant embedding tables sharded
over the mesh (ref: example/model-parallel/matrix_factorization/ — there,
manual group2ctx placement across GPUs; here a tensor-parallel sharding
spec on one mesh, the TPU-native equivalent of per-layer placement).

The reference's group2ctx API itself is ALSO supported (r5):
``Symbol.bind(..., group2ctx={'dev1': ctx, ...})`` places ctx-group
annotated nodes per device with automatic cross-group transfers —
tests/test_module.py::test_group2ctx_model_parallel runs this exact
model shape through it. Prefer the mesh sharding below for performance
(one compiled program); group2ctx is the API-parity path.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python example/model-parallel/matrix_factorization.py --shards 4
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--users", type=int, default=2000)
    p.add_argument("--items", type=int, default=4000)
    p.add_argument("--factor", type=int, default=64)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--shards", type=int, default=1,
                   help="ways to shard the embedding factor dim (tp)")
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.parallel import create_mesh

    devs = jax.devices()[:max(args.shards, 1)]
    mesh = create_mesh(devices=devs, tp=len(devs))
    raw = mesh.mesh

    rs = np.random.RandomState(0)
    # ground-truth low-rank structure
    true_u = rs.randn(args.users, 8).astype("float32")
    true_i = rs.randn(args.items, 8).astype("float32")

    shard = NamedSharding(raw, P(None, "tp"))  # factor dim over the mesh
    params = {
        "user": jax.device_put(
            (rs.randn(args.users, args.factor) * 0.05).astype("float32"),
            shard),
        "item": jax.device_put(
            (rs.randn(args.items, args.factor) * 0.05).astype("float32"),
            shard),
    }

    def loss_fn(params, u, i, r):
        pu = params["user"][u]              # [B, F] — F sharded over tp
        pi = params["item"][i]
        pred = jnp.sum(pu * pi, axis=-1)    # psum over tp via GSPMD
        return jnp.mean((pred - r) ** 2)

    @jax.jit
    def step(params, u, i, r):
        loss, g = jax.value_and_grad(loss_fn)(params, u, i, r)
        return ({k: params[k] - args.lr * g[k] for k in params}, loss)

    for it in range(args.steps):
        u = rs.randint(0, args.users, args.batch)
        i = rs.randint(0, args.items, args.batch)
        r = (true_u[u] * true_i[i]).sum(1).astype("float32")
        params, loss = step(params, jnp.asarray(u), jnp.asarray(i),
                            jnp.asarray(r))
        if it % 10 == 0 or it == args.steps - 1:
            print("step %3d rmse %.4f" % (it, float(loss) ** 0.5))
    print("embedding shard spec:", params["user"].sharding)


if __name__ == "__main__":
    main()
