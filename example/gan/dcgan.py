#!/usr/bin/env python
"""DCGAN on synthetic 32x32 data (ref: example/gan/dcgan.py — same
generator/discriminator shapes and alternating Trainer updates).

    python example/gan/dcgan.py --epochs 1
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, autograd, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def build_netG(ngf=32, nc=3):
    netG = nn.Sequential()
    netG.add(
        nn.Conv2DTranspose(ngf * 4, 4, 1, 0, use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(nc, 4, 2, 1, use_bias=False),
        nn.Activation("tanh"))
    return netG


def build_netD(ndf=32):
    netD = nn.Sequential()
    netD.add(
        nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
        nn.LeakyReLU(0.2),
        nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),
        nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),
        nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return netD


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--nz", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.0002)
    p.add_argument("--batches", type=int, default=20)
    args = p.parse_args()

    rs = np.random.RandomState(0)
    netG, netD = build_netG(), build_netD()
    netG.initialize(mx.initializer.Normal(0.02))
    netD.initialize(mx.initializer.Normal(0.02))
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    B = args.batch_size
    real_label = nd.ones((B,))
    fake_label = nd.zeros((B,))
    for epoch in range(args.epochs):
        for it in range(args.batches):
            # "real" data: smooth blobs (self-contained stand-in)
            real = nd.array(np.tanh(
                rs.rand(B, 3, 32, 32) * 2 - 1).astype("float32"))
            noise = nd.array(rs.randn(B, args.nz, 1, 1).astype("float32"))

            # --- update D ---
            with autograd.record():
                out_real = netD(real).reshape((-1,))
                errD_real = loss_fn(out_real, real_label)
                fake = netG(noise)
                out_fake = netD(fake.detach()).reshape((-1,))
                errD_fake = loss_fn(out_fake, fake_label)
                errD = errD_real + errD_fake
            errD.backward()
            trainerD.step(B)

            # --- update G ---
            with autograd.record():
                out = netD(netG(noise)).reshape((-1,))
                errG = loss_fn(out, real_label)
            errG.backward()
            trainerG.step(B)
        print("epoch %d: lossD %.4f lossG %.4f"
              % (epoch, float(errD.mean().asscalar()),
                 float(errG.mean().asscalar())))
    print("done; generator output shape:",
          netG(nd.array(rs.randn(2, args.nz, 1, 1)
                        .astype("float32"))).shape)


if __name__ == "__main__":
    main()
