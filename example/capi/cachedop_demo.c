/* Drive the jit seam from pure C: create a CachedOp from a Symbol,
 * invoke it twice with the same input signature, and prove the second
 * call hit the compile cache (VERDICT r3 item 3 done-criterion).
 *
 * ref: include/mxnet/c_api.h:1241 MXCreateCachedOp, :1257
 * MXInvokeCachedOp, :1252 MXFreeCachedOp. MXTCachedOpGetStats is this
 * framework's observability extension: (calls, compiles) — compiles
 * counts distinct input signatures, i.e. XLA trace+compile events.
 *
 * Usage: cachedop_demo <sym.json>
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

extern const char* MXTGetLastError(void);
extern int MXTNDArrayFromData(const int64_t*, uint32_t, int, const void*,
                              size_t, void**);
extern int MXTNDArrayFree(void*);
extern int MXTNDArraySyncCopyToCPU(void*, void*, size_t);
extern int MXTSymbolCreateFromFile(const char*, void**);
extern int MXTSymbolFree(void*);
extern int MXTCachedOpCreate(void*, uint32_t, const char**, const char**,
                             void**);
extern int MXTCachedOpInvoke(void*, uint32_t, void**, uint32_t*, void**,
                             uint32_t);
extern int MXTCachedOpGetStats(void*, uint64_t*, uint64_t*);
extern int MXTCachedOpFree(void*);

#define CHECK(call)                                              \
  do {                                                           \
    if ((call) != 0) {                                           \
      fprintf(stderr, "FAILED %s: %s\n", #call, MXTGetLastError()); \
      return 1;                                                  \
    }                                                            \
  } while (0)

static void* make_batch(int64_t n, int64_t d, float fill) {
  int64_t shape[2];
  float* buf = (float*)malloc((size_t)(n * d) * sizeof(float));
  void* arr = NULL;
  int64_t i;
  shape[0] = n;
  shape[1] = d;
  for (i = 0; i < n * d; ++i) buf[i] = fill + (float)(i % 7) * 0.1f;
  if (MXTNDArrayFromData(shape, 2, 0, buf, (size_t)(n * d) * sizeof(float),
                         &arr) != 0) {
    fprintf(stderr, "FromData: %s\n", MXTGetLastError());
    exit(1);
  }
  free(buf);
  return arr;
}

int main(int argc, char** argv) {
  void* sym = NULL;
  void* op = NULL;
  void* outs[8];
  uint32_t num_outputs = 0;
  uint64_t calls = 0, compiles = 0;
  const char* flag_keys[] = {"static_alloc"};
  const char* flag_vals[] = {"True"};
  float out_buf[4 * 2];
  float first_val;

  if (argc < 2) {
    fprintf(stderr, "usage: %s <sym.json>\n", argv[0]);
    return 2;
  }
  CHECK(MXTSymbolCreateFromFile(argv[1], &sym));
  CHECK(MXTCachedOpCreate(sym, 1, flag_keys, flag_vals, &op));

  /* two invocations, identical signature -> one compile */
  {
    void* x = make_batch(4, 3, 1.0f);
    void* w = make_batch(2, 3, 0.5f);
    void* inputs[2];
    inputs[0] = x;
    inputs[1] = w;
    CHECK(MXTCachedOpInvoke(op, 2, inputs, &num_outputs, outs, 8));
    if (num_outputs != 1) {
      fprintf(stderr, "expected 1 output, got %u\n", num_outputs);
      return 1;
    }
    CHECK(MXTNDArraySyncCopyToCPU(outs[0], out_buf, sizeof(out_buf)));
    first_val = out_buf[0];
    CHECK(MXTNDArrayFree(outs[0]));
    CHECK(MXTCachedOpInvoke(op, 2, inputs, &num_outputs, outs, 8));
    CHECK(MXTNDArraySyncCopyToCPU(outs[0], out_buf, sizeof(out_buf)));
    if (out_buf[0] != first_val) {
      fprintf(stderr, "second call changed the result: %f vs %f\n",
              out_buf[0], first_val);
      return 1;
    }
    CHECK(MXTNDArrayFree(outs[0]));
    CHECK(MXTNDArrayFree(x));
    CHECK(MXTNDArrayFree(w));
  }
  CHECK(MXTCachedOpGetStats(op, &calls, &compiles));
  printf("after 2 same-shape calls: calls=%llu compiles=%llu\n",
         (unsigned long long)calls, (unsigned long long)compiles);
  if (calls != 2 || compiles != 1) {
    fprintf(stderr, "cache MISS on second call (calls=%llu compiles=%llu)\n",
            (unsigned long long)calls, (unsigned long long)compiles);
    return 1;
  }

  /* a new batch size is a new signature -> one more compile */
  {
    void* x = make_batch(8, 3, 2.0f);
    void* w = make_batch(2, 3, 0.5f);
    void* inputs[2];
    inputs[0] = x;
    inputs[1] = w;
    CHECK(MXTCachedOpInvoke(op, 2, inputs, &num_outputs, outs, 8));
    CHECK(MXTNDArrayFree(outs[0]));
    CHECK(MXTNDArrayFree(x));
    CHECK(MXTNDArrayFree(w));
  }
  CHECK(MXTCachedOpGetStats(op, &calls, &compiles));
  printf("after resized call: calls=%llu compiles=%llu\n",
         (unsigned long long)calls, (unsigned long long)compiles);
  if (calls != 3 || compiles != 2) {
    fprintf(stderr, "expected a recompile for the new signature\n");
    return 1;
  }

  CHECK(MXTCachedOpFree(op));
  CHECK(MXTSymbolFree(sym));
  printf("CachedOp C ABI OK\n");
  return 0;
}
