/* Train an MLP classifier through the C ABI — no Python in this file.
 *
 * The cpp-package-style demo the reference enables via its C surface
 * (ref: cpp-package/include/mxnet-cpp/ndarray.h over include/mxnet/
 * c_api.h): create NDArrays, invoke registered ops by name, record an
 * autograd tape, backward, and apply SGD updates, all via MXT* entry
 * points from libmxnet_tpu.so. Data is synthetic MNIST-shaped
 * (784-dim inputs, 10 classes, linearly separable blobs) so the demo
 * is self-contained; the assertion is that training loss drops 5x.
 *
 * Build (see tests/test_capi_train.py which runs this in CI):
 *   gcc -O2 train_mnist.c -o train_mnist \
 *       -L$REPO/mxnet_tpu -lmxnet_tpu -Wl,-rpath,$REPO/mxnet_tpu
 *   PYTHONPATH=$REPO JAX_PLATFORMS=cpu ./train_mnist
 */
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

/* ---- ABI (mirrors src/c_api_runtime.cc declarations) ---- */
extern const char* MXTGetLastError(void);
extern int MXTNDArrayCreate(const int64_t* shape, uint32_t ndim, int dtype,
                            void** out);
extern int MXTNDArrayFromData(const int64_t* shape, uint32_t ndim,
                              int dtype, const void* data, size_t nbytes,
                              void** out);
extern int MXTNDArrayFree(void* h);
extern int MXTNDArraySyncCopyToCPU(void* h, void* data, size_t nbytes);
extern int MXTImperativeInvoke(const char* op, uint32_t nin, void** in,
                               uint32_t nparam, const char** keys,
                               const char** vals, uint32_t* nout,
                               void** out, uint32_t max_out);
extern int MXTAutogradMarkVariables(uint32_t n, void** h);
extern int MXTAutogradSetIsRecording(int rec);
extern int MXTAutogradBackward(uint32_t n, void** out);
extern int MXTNDArrayGetGrad(void* h, void** grad);

#define CHECK(rc) do { \
    if ((rc) != 0) { \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, \
              MXTGetLastError()); \
      exit(1); \
    } } while (0)

#define F32 0

static void* invoke1(const char* op, uint32_t nin, void** in,
                     uint32_t nparam, const char** keys,
                     const char** vals) {
  void* outs[4];
  uint32_t nout = 0;
  CHECK(MXTImperativeInvoke(op, nin, in, nparam, keys, vals, &nout, outs,
                            4));
  /* ops like BatchNorm return extras; the primary output is outs[0] —
     free the rest */
  for (uint32_t i = 1; i < nout; ++i) MXTNDArrayFree(outs[i]);
  return outs[0];
}

int main(void) {
  const int N = 256, D = 784, H = 64, C = 10, EPOCHS = 30;
  const float LR = 0.5f;

  /* synthetic separable data: class c has mean one-hot spread */
  float* x = (float*)malloc((size_t)N * D * sizeof(float));
  float* y = (float*)malloc((size_t)N * sizeof(float));
  srand(7);
  for (int i = 0; i < N; ++i) {
    int c = i % C;
    y[i] = (float)c;
    for (int j = 0; j < D; ++j) {
      float noise = ((float)rand() / RAND_MAX - 0.5f) * 0.5f;
      x[i * D + j] = noise + ((j % C) == c ? 1.0f : 0.0f);
    }
  }

  /* parameters as C buffers; uploaded fresh each step after updates */
  float* w1 = (float*)calloc((size_t)D * H, sizeof(float));
  float* b1 = (float*)calloc((size_t)H, sizeof(float));
  float* w2 = (float*)calloc((size_t)H * C, sizeof(float));
  float* b2 = (float*)calloc((size_t)C, sizeof(float));
  for (int i = 0; i < D * H; ++i)
    w1[i] = ((float)rand() / RAND_MAX - 0.5f) * 0.05f;
  for (int i = 0; i < H * C; ++i)
    w2[i] = ((float)rand() / RAND_MAX - 0.5f) * 0.05f;

  int64_t xs[2] = {N, D}, ys1[1] = {N};
  int64_t w1s[2] = {H, D}, b1s[1] = {H}, w2s[2] = {C, H}, b2s[1] = {C};
  /* note FullyConnected weight layout is (num_hidden, input_dim) like
     the reference */
  float* w1t = (float*)malloc((size_t)D * H * sizeof(float));
  float* w2t = (float*)malloc((size_t)H * C * sizeof(float));

  void* xa = NULL;
  void* ya = NULL;
  CHECK(MXTNDArrayFromData(xs, 2, F32, x, (size_t)N * D * 4, &xa));
  CHECK(MXTNDArrayFromData(ys1, 1, F32, y, (size_t)N * 4, &ya));

  float first_loss = -1.0f, last_loss = -1.0f;
  for (int ep = 0; ep < EPOCHS; ++ep) {
    /* upload parameters (row-major (H,D)/(C,H)) */
    for (int i = 0; i < H; ++i)
      for (int j = 0; j < D; ++j) w1t[i * D + j] = w1[j * H + i];
    for (int i = 0; i < C; ++i)
      for (int j = 0; j < H; ++j) w2t[i * H + j] = w2[j * C + i];
    void* W1 = NULL; void* B1 = NULL; void* W2 = NULL; void* B2 = NULL;
    CHECK(MXTNDArrayFromData(w1s, 2, F32, w1t, (size_t)D * H * 4, &W1));
    CHECK(MXTNDArrayFromData(b1s, 1, F32, b1, (size_t)H * 4, &B1));
    CHECK(MXTNDArrayFromData(w2s, 2, F32, w2t, (size_t)H * C * 4, &W2));
    CHECK(MXTNDArrayFromData(b2s, 1, F32, b2, (size_t)C * 4, &B2));
    void* params[4] = {W1, B1, W2, B2};
    CHECK(MXTAutogradMarkVariables(4, params));

    CHECK(MXTAutogradSetIsRecording(1));
    const char* fck[] = {"num_hidden"};
    const char* fcv1[] = {"64"};
    void* in1[3] = {xa, W1, B1};
    void* h1 = invoke1("FullyConnected", 3, in1, 1, fck, fcv1);
    const char* ak[] = {"act_type"};
    const char* av[] = {"relu"};
    void* h1r = invoke1("Activation", 1, &h1, 1, ak, av);
    const char* fcv2[] = {"10"};
    void* in2[3] = {h1r, W2, B2};
    void* logits = invoke1("FullyConnected", 3, in2, 1, fck, fcv2);
    /* softmax cross entropy: returns per-batch loss (ref:
       softmax_cross_entropy op) */
    void* in3[2] = {logits, ya};
    void* loss = invoke1("softmax_cross_entropy", 2, in3, 0, NULL, NULL);
    CHECK(MXTAutogradSetIsRecording(0));
    CHECK(MXTAutogradBackward(1, &loss));

    float lval = 0.0f;
    CHECK(MXTNDArraySyncCopyToCPU(loss, &lval, sizeof lval));
    lval /= (float)N;
    if (ep == 0) first_loss = lval;
    last_loss = lval;

    /* SGD: pull grads, update C-side buffers */
    void* grads[4] = {NULL, NULL, NULL, NULL};
    for (int p = 0; p < 4; ++p) CHECK(MXTNDArrayGetGrad(params[p], &grads[p]));
    float* gw1 = (float*)malloc((size_t)D * H * 4);
    float* gb1 = (float*)malloc((size_t)H * 4);
    float* gw2 = (float*)malloc((size_t)H * C * 4);
    float* gb2 = (float*)malloc((size_t)C * 4);
    CHECK(MXTNDArraySyncCopyToCPU(grads[0], gw1, (size_t)D * H * 4));
    CHECK(MXTNDArraySyncCopyToCPU(grads[1], gb1, (size_t)H * 4));
    CHECK(MXTNDArraySyncCopyToCPU(grads[2], gw2, (size_t)H * C * 4));
    CHECK(MXTNDArraySyncCopyToCPU(grads[3], gb2, (size_t)C * 4));
    float inv = LR / (float)N;  /* loss was summed over batch */
    for (int i = 0; i < H; ++i)
      for (int j = 0; j < D; ++j) w1[j * H + i] -= inv * gw1[i * D + j];
    for (int i = 0; i < H; ++i) b1[i] -= inv * gb1[i];
    for (int i = 0; i < C; ++i)
      for (int j = 0; j < H; ++j) w2[j * C + i] -= inv * gw2[i * H + j];
    for (int i = 0; i < C; ++i) b2[i] -= inv * gb2[i];
    free(gw1); free(gb1); free(gw2); free(gb2);
    for (int p = 0; p < 4; ++p) MXTNDArrayFree(grads[p]);
    MXTNDArrayFree(h1); MXTNDArrayFree(h1r); MXTNDArrayFree(logits);
    MXTNDArrayFree(loss);
    for (int p = 0; p < 4; ++p) MXTNDArrayFree(params[p]);

    if (ep % 10 == 0) printf("epoch %d loss %.4f\n", ep, (double)lval);
  }

  printf("first %.4f last %.4f\n", (double)first_loss, (double)last_loss);
  if (!(last_loss < first_loss / 5.0f)) {
    fprintf(stderr, "FAIL: loss did not drop 5x\n");
    return 1;
  }
  printf("C-ABI MNIST training OK\n");
  MXTNDArrayFree(xa);
  MXTNDArrayFree(ya);
  free(x); free(y); free(w1); free(b1); free(w2); free(b2);
  free(w1t); free(w2t);
  return 0;
}
