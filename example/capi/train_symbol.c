/* Train a Symbol loaded from JSON, entirely through the C ABI.
 *
 * The VERDICT done-criterion for the widened C surface: a C program
 * binds a Symbol from JSON, feeds it from a DataIter, trains it with a
 * KVStore-held optimizer, and writes a checkpoint Python loads back.
 * Families exercised: MXTSymbol*, MXTExecutor*, MXTKVStore*,
 * MXTDataIter*, MXTNDArraySave (ref: include/mxnet/c_api.h —
 * MXSymbolCreateFromJSON, MXExecutorSimpleBindEx, MXKVStorePushPullEx,
 * MXDataIterNext, MXNDArraySave :659).
 *
 * Usage: train_symbol <sym.json> <data.csv> <label.csv> <out.params>
 * Prints "epoch <i> loss <v>" lines and "final loss <v>".
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* ---- ABI declarations (mirror src/c_api_symbol.cc) ---- */
extern const char* MXTGetLastError(void);
extern int MXTNDArrayFree(void*);
extern int MXTNDArrayGetShape(void*, uint32_t*, int64_t*);
extern int MXTNDArraySyncCopyToCPU(void*, void*, size_t);
extern int MXTNDArraySyncCopyFromCPU(void*, const void*, size_t);
extern int MXTNDArraySave(const char*, uint32_t, void**, const char**);
extern int MXTSymbolCreateFromFile(const char*, void**);
extern int MXTSymbolListArguments(void*, uint32_t*, const char***);
extern int MXTSymbolFree(void*);
extern int MXTExecutorSimpleBind(void*, uint32_t, const char**,
                                 const uint32_t*, const int64_t*,
                                 const char*, void**);
extern int MXTExecutorForward(void*, int);
extern int MXTExecutorBackward(void*, uint32_t, void**);
extern int MXTExecutorOutputs(void*, uint32_t*, void**, uint32_t);
extern int MXTExecutorArgArray(void*, const char*, void**);
extern int MXTExecutorGradArray(void*, const char*, void**);
extern int MXTExecutorFree(void*);
extern int MXTKVStoreCreate(const char*, void**);
extern int MXTKVStoreInitEx(void*, const char*, void*);
extern int MXTKVStorePushEx(void*, const char*, void*, int);
extern int MXTKVStorePullEx(void*, const char*, void*, int);
extern int MXTKVStoreSetOptimizer(void*, const char*, uint32_t,
                                  const char**, const char**);
extern int MXTKVStoreFree(void*);
extern int MXTDataIterCreate(const char*, uint32_t, const char**,
                             const char**, void**);
extern int MXTDataIterNext(void*, int*);
extern int MXTDataIterGetData(void*, void**);
extern int MXTDataIterGetLabel(void*, void**);
extern int MXTDataIterBeforeFirst(void*);
extern int MXTDataIterFree(void*);

#define CHECK(call)                                              \
  do {                                                           \
    if ((call) != 0) {                                           \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,    \
              MXTGetLastError());                                \
      return 1;                                                  \
    }                                                            \
  } while (0)

#define BATCH 8
#define FEAT 4

static int copy_between(void* src, void* dst, size_t nbytes) {
  /* device->host->device value copy between two NDArray handles */
  float buf[BATCH * FEAT];
  if (nbytes > sizeof(buf)) return 1;
  if (MXTNDArraySyncCopyToCPU(src, buf, nbytes) != 0) return 1;
  return MXTNDArraySyncCopyFromCPU(dst, buf, nbytes);
}

int main(int argc, char** argv) {
  if (argc != 5) {
    fprintf(stderr,
            "usage: %s sym.json data.csv label.csv out.params\n", argv[0]);
    return 2;
  }

  /* -- Symbol from JSON ------------------------------------------------ */
  void* sym = NULL;
  CHECK(MXTSymbolCreateFromFile(argv[1], &sym));
  uint32_t nargs = 0;
  const char** arg_names = NULL;
  CHECK(MXTSymbolListArguments(sym, &nargs, &arg_names));
  printf("symbol has %u arguments\n", nargs);

  /* copy names out of the thread-local return buffer before other ABI
   * calls reuse it */
  char names_buf[16][64];
  if (nargs > 16) return 2;
  for (uint32_t i = 0; i < nargs; ++i) {
    strncpy(names_buf[i], arg_names[i], 63);
    names_buf[i][63] = '\0';
  }

  /* -- bind ------------------------------------------------------------- */
  const char* prov_names[2] = {"data", "label"};
  uint32_t ndims[2] = {2, 2};
  int64_t shapes_flat[4] = {BATCH, FEAT, BATCH, 1};
  void* exec = NULL;
  CHECK(MXTExecutorSimpleBind(sym, 2, prov_names, ndims, shapes_flat,
                              "write", &exec));

  /* -- KVStore with server-side SGD ------------------------------------- */
  void* kv = NULL;
  CHECK(MXTKVStoreCreate("local", &kv));
  const char* opt_keys[1] = {"learning_rate"};
  const char* opt_vals[1] = {"0.05"};
  /* trainable args = everything except data/label */
  void* weights[16];
  const char* wnames[16];
  uint32_t nweights = 0;
  for (uint32_t i = 0; i < nargs; ++i) {
    if (strcmp(names_buf[i], "data") == 0 ||
        strcmp(names_buf[i], "label") == 0)
      continue;
    void* w = NULL;
    CHECK(MXTExecutorArgArray(exec, names_buf[i], &w));
    weights[nweights] = w;
    wnames[nweights] = names_buf[i];
    ++nweights;
    CHECK(MXTKVStoreInitEx(kv, names_buf[i], w));
  }
  CHECK(MXTKVStoreSetOptimizer(kv, "sgd", 1, opt_keys, opt_vals));

  /* -- data ------------------------------------------------------------- */
  const char* it_keys[5] = {"data_csv", "data_shape", "label_csv",
                            "label_shape", "batch_size"};
  const char* it_vals[5] = {argv[2], "(4,)", argv[3], "(1,)", "8"};
  void* iter = NULL;
  CHECK(MXTDataIterCreate("CSVIter", 5, it_keys, it_vals, &iter));

  void* data_arr = NULL;
  void* label_arr = NULL;
  CHECK(MXTExecutorArgArray(exec, "data", &data_arr));
  CHECK(MXTExecutorArgArray(exec, "label", &label_arr));

  /* -- training loop ---------------------------------------------------- */
  double final_loss = 0.0;
  for (int epoch = 0; epoch < 30; ++epoch) {
    CHECK(MXTDataIterBeforeFirst(iter));
    int more = 0;
    double epoch_loss = 0.0;
    int nbatch = 0;
    for (;;) {
      CHECK(MXTDataIterNext(iter, &more));
      if (!more) break;
      void* bd = NULL;
      void* bl = NULL;
      CHECK(MXTDataIterGetData(iter, &bd));
      CHECK(MXTDataIterGetLabel(iter, &bl));
      if (copy_between(bd, data_arr, BATCH * FEAT * 4) != 0 ||
          copy_between(bl, label_arr, BATCH * 1 * 4) != 0) {
        fprintf(stderr, "batch copy failed\n");
        return 1;
      }
      MXTNDArrayFree(bd);
      MXTNDArrayFree(bl);

      CHECK(MXTExecutorForward(exec, 1));
      CHECK(MXTExecutorBackward(exec, 0, NULL));

      /* push grads; pull back optimizer-updated weights */
      for (uint32_t i = 0; i < nweights; ++i) {
        void* g = NULL;
        CHECK(MXTExecutorGradArray(exec, wnames[i], &g));
        CHECK(MXTKVStorePushEx(kv, wnames[i], g, 0));
        CHECK(MXTKVStorePullEx(kv, wnames[i], weights[i], 0));
        MXTNDArrayFree(g);
      }

      /* loss = mean of the LinearRegressionOutput residual^2 — the
       * output equals the prediction; compute vs label on host */
      uint32_t nout = 0;
      void* outs[4];
      CHECK(MXTExecutorOutputs(exec, &nout, outs, 4));
      float pred[BATCH], lab[BATCH];
      CHECK(MXTNDArraySyncCopyToCPU(outs[0], pred, sizeof(pred)));
      CHECK(MXTNDArraySyncCopyToCPU(label_arr, lab, sizeof(lab)));
      for (uint32_t i = 0; i < nout; ++i) MXTNDArrayFree(outs[i]);
      double l = 0.0;
      for (int i = 0; i < BATCH; ++i) {
        double d = pred[i] - lab[i];
        l += d * d;
      }
      epoch_loss += l / BATCH;
      ++nbatch;
    }
    final_loss = epoch_loss / (nbatch > 0 ? nbatch : 1);
    if (epoch % 10 == 0 || epoch == 29)
      printf("epoch %d loss %.6f\n", epoch, final_loss);
  }
  printf("final loss %.6f\n", final_loss);

  /* -- checkpoint -------------------------------------------------------- */
  CHECK(MXTNDArraySave(argv[4], nweights, weights, wnames));
  printf("saved %u arrays to %s\n", nweights, argv[4]);

  for (uint32_t i = 0; i < nweights; ++i) MXTNDArrayFree(weights[i]);
  MXTNDArrayFree(data_arr);
  MXTNDArrayFree(label_arr);
  MXTDataIterFree(iter);
  MXTKVStoreFree(kv);
  MXTExecutorFree(exec);
  MXTSymbolFree(sym);
  return 0;
}
