#!/usr/bin/env python
"""Multi-task learning: one trunk, two heads, joint loss
(ref: example/multi-task/example_multi_task.py — same two-softmax-heads
shape over a shared trunk).

    python example/multi-task/multi_task.py --epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, autograd, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


class MultiTaskNet(gluon.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.trunk = nn.Sequential()
        self.trunk.add(nn.Dense(64, activation="relu"),
                       nn.Dense(32, activation="relu"))
        self.head_a = nn.Dense(4)    # task A: 4-way classification
        self.head_b = nn.Dense(1)    # task B: regression

    def forward(self, x):
        h = self.trunk(x)
        return self.head_a(h), self.head_b(h)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()

    rs = np.random.RandomState(0)
    X = rs.rand(512, 10).astype("float32")
    Ya = (X[:, :4].argmax(axis=1)).astype("float32")       # class = argmax
    Yb = X.sum(axis=1, keepdims=True).astype("float32")    # sum regression

    ds = gluon.data.ArrayDataset(X, Ya, Yb)
    loader = gluon.data.DataLoader(ds, batch_size=args.batch_size,
                                   shuffle=True)
    net = MultiTaskNet()
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.005})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    l2 = gluon.loss.L2Loss()
    acc = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        acc.reset()
        tot = cnt = 0
        for xb, ya, yb in loader:
            with autograd.record():
                la, lb = net(xb)
                L = ce(la, ya) + 0.5 * l2(lb, yb)
            L.backward()
            trainer.step(xb.shape[0])
            acc.update([ya], [la])
            tot += float(L.mean().asscalar())
            cnt += 1
        print("epoch %d: joint loss %.4f, task-A acc %.3f"
              % (epoch, tot / cnt, acc.get()[1]))


if __name__ == "__main__":
    main()
