#!/usr/bin/env python
"""Train MNIST with the Gluon API (ref: example/gluon/mnist/mnist.py —
same script shape: DataLoader + HybridSequential + Trainer loop).

    python example/gluon/mnist.py --epochs 3 --hybridize
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, autograd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--hybridize", action="store_true")
    parser.add_argument("--tpu", action="store_true",
                        help="place on the TPU (ref --cuda)")
    args = parser.parse_args()

    from mxnet_tpu.gluon.data.vision import MNIST, transforms
    trans = transforms.Compose([transforms.ToTensor()])
    train_data = gluon.data.DataLoader(
        MNIST(train=True).transform_first(trans),
        batch_size=args.batch_size, shuffle=True)
    val_data = gluon.data.DataLoader(
        MNIST(train=False).transform_first(trans),
        batch_size=args.batch_size)

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    ctx = mx.tpu() if args.tpu else mx.cpu()
    net.initialize(ctx=ctx)
    if args.hybridize:
        net.hybridize()  # whole forward+backward -> one XLA program

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for data, label in train_data:
            data = data.reshape((data.shape[0], -1))
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update(label, out)
        name, train_acc = metric.get()
        metric.reset()
        for data, label in val_data:
            metric.update(label, net(data.reshape((data.shape[0], -1))))
        _, val_acc = metric.get()
        print("epoch %d: train %s %.4f, val %.4f"
              % (epoch, name, train_acc, val_acc))


if __name__ == "__main__":
    main()
