#!/usr/bin/env python
"""Distributed transformer LM training — the modern flagship the 2019
reference lacks (its sequence story is bucketed RNNs; SURVEY §5).

One mesh, every parallelism axis as a sharding choice:

    # single chip / virtual CPU devices
    python example/transformer/train_lm.py --steps 5

    # 8 virtual devices: 2-way data x 2-way tensor x 2-way sequence
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python example/transformer/train_lm.py --dp 2 --tp 2 --sp 2 \
        --attn ring --steps 5

    # GPipe pipeline: 2 stages x 2-way data
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python example/transformer/train_lm.py --pp 2 --dp 2 --sp 2 \
        --microbatch 2 --steps 5
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--microbatch", type=int, default=1)
    p.add_argument("--attn", default="local",
                   choices=["local", "ring", "ulysses", "blockwise"])
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--experts", type=int, default=0)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.parallel import create_mesh
    from mxnet_tpu.parallel import transformer as T

    n_needed = args.dp * args.tp * args.sp * args.pp * args.ep
    devs = jax.devices()
    assert len(devs) >= n_needed, \
        "need %d devices, have %d (set XLA_FLAGS=" \
        "--xla_force_host_platform_device_count=N)" % (n_needed, len(devs))

    mesh_axes = {k: v for k, v in dict(
        dp=args.dp, tp=args.tp, sp=args.sp, pp=args.pp,
        ep=args.ep).items() if v > 1} or {"dp": 1}
    mesh = create_mesh(devices=devs[:n_needed], **mesh_axes)
    cfg = T.TransformerConfig(
        vocab_size=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=args.heads, ffn_hidden=args.dim * 4, max_seq_len=args.seq,
        attn_mode=args.attn, pp=args.pp, n_microbatch=args.microbatch,
        num_experts=args.experts)
    init_fn, step_fn = T.make_train_step(cfg, mesh)

    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, args.vocab, (args.batch, args.seq)),
                       jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        for step in range(args.steps):
            state, loss = step_fn(state, toks, tgts)
            print("step %d loss %.4f" % (step, float(loss)))
    print("mesh:", mesh_axes, "attn:", args.attn)


if __name__ == "__main__":
    main()
