"""SSD-style detector training on synthetic data — the reference's
flagship detection workload shape (ref: example/ssd/ in the broader
MXNet ecosystem; ops from src/operator/contrib/multibox_*.cc).

Pipeline: conv backbone -> per-location class+box heads ->
MultiBoxPrior anchors -> MultiBoxTarget assignment (bipartite match +
hard negative mining) -> joint softmax-CE (classes) + smooth-L1 (boxes)
loss -> SGD. Inference: MultiBoxDetection decodes + NMS.

Synthetic task: one axis-aligned bright square per image; the detector
must learn to classify its anchor and regress its box. Run:
  JAX_PLATFORMS=cpu PYTHONPATH=. python example/ssd/train_ssd.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def make_batch(rng, batch=8, size=32):
    """Images with one white square; label rows (cls, x1, y1, x2, y2)
    in [0,1] corner coords, padded with -1 rows."""
    x = rng.rand(batch, 3, size, size).astype("float32") * 0.1
    labels = onp.full((batch, 2, 5), -1.0, "float32")
    for i in range(batch):
        w = rng.randint(8, 16)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - w)
        x[i, :, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [0, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + w) / size]
    return mx.nd.array(x), mx.nd.array(labels)


def make_rec_dataset(path, n=64, size=64, seed=0):
    """Write the synthetic-squares dataset as a JPEG .rec with
    reference-format detection labels ([hdr_w, obj_w, cls, x1..y2]),
    so training runs through the REAL detection pipeline:
    .rec -> ImageDetIter -> label-aware crop/pad/flip augmenters."""
    import cv2
    from mxnet_tpu import recordio
    rng = onp.random.RandomState(seed)
    idx = path.replace(".rec", ".idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n):
        img = (rng.rand(size, size, 3) * 25).astype(onp.uint8)
        sq = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - sq)
        y0 = rng.randint(0, size - sq)
        img[y0:y0 + sq, x0:x0 + sq] = 255
        label = [2.0, 5.0, 0.0, x0 / size, y0 / size,
                 (x0 + sq) / size, (y0 + sq) / size]
        header = recordio.IRHeader(0, label, i, 0)
        w.write_idx(i, recordio.pack_img(header, img, quality=95))
    w.close()
    return path, idx


def make_det_iter(path_imgrec, path_imgidx, batch_size=8, data_size=32):
    """The detection input pipeline (ref: detection.py ImageDetIter +
    CreateDetAugmenter): random constrained crop, random expansion pad,
    horizontal flip — all label-aware."""
    return mx.image.ImageDetIter(
        batch_size=batch_size, data_shape=(3, data_size, data_size),
        path_imgrec=path_imgrec, path_imgidx=path_imgidx, shuffle=True,
        rand_crop=0.5, rand_pad=0.5, rand_mirror=True,
        min_object_covered=0.5, std=onp.array([255.0, 255.0, 255.0]))


class TinySSD(gluon.HybridBlock):
    def __init__(self, num_classes=1, num_anchors=4, **kw):
        super().__init__(**kw)
        self.num_classes = num_classes
        self.backbone = gluon.nn.HybridSequential()
        for ch in (16, 32):
            self.backbone.add(gluon.nn.Conv2D(ch, 3, padding=1),
                              gluon.nn.Activation("relu"),
                              gluon.nn.MaxPool2D(2))
        # per-location heads: (classes+1) scores and 4 box offsets per
        # anchor
        self.cls_head = gluon.nn.Conv2D(num_anchors * (num_classes + 1),
                                        3, padding=1)
        self.box_head = gluon.nn.Conv2D(num_anchors * 4, 3, padding=1)

    def hybrid_forward(self, F, x):
        feat = self.backbone(x)
        cls = self.cls_head(feat)
        box = self.box_head(feat)
        return feat, cls, box


def train(epochs=150, seed=0, log=print):
    rng = onp.random.RandomState(seed)
    net = TinySSD()
    net.initialize()
    x0, _ = make_batch(rng)
    net(x0)  # shape init

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    sizes = (0.3, 0.45)
    ratios = (1.0, 2.0, 0.5)

    losses = []
    x, labels = make_batch(rng, batch=16)  # fixed set: the demo shows
    # the pipeline learns it (the reference examples train ImageNet-scale
    # data; synthetic-fixed keeps this runnable in CI seconds)
    for ep in range(epochs):
        with autograd.record():
            feat, cls, box = net(x)
            B = x.shape[0]
            anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes,
                                               ratios=ratios)
            anchors = anchors.reshape(1, -1, 4)
            A = anchors.shape[1]
            # predictions must follow MultiBoxPrior's (H, W, anchor)
            # ordering: NCHW -> NHWC -> (B, A, C+1) with channel
            # interpreted (anchor, class)
            cls_pred = nd.transpose(cls, axes=(0, 2, 3, 1)) \
                .reshape(B, A, 2)
            cls_pred_t = nd.transpose(cls_pred, axes=(0, 2, 1))
            box_flat = nd.transpose(box, axes=(0, 2, 3, 1)).reshape(B, -1)
            loc_target, loc_mask, cls_target = nd.contrib.MultiBoxTarget(
                anchors, labels, cls_pred_t,
                overlap_threshold=0.5, negative_mining_ratio=3.0,
                negative_mining_thresh=0.5)
            flat_pred = cls_pred.reshape(-1, 2)
            flat_tgt = cls_target.reshape(-1)
            # ignore_label=-1 anchors (neither positive nor mined
            # negative) must not contribute to the CE — the reference's
            # SoftmaxOutput uses ignore_label for exactly this
            keep = flat_tgt >= 0
            safe_tgt = nd.where(keep, flat_tgt,
                                nd.zeros_like(flat_tgt))
            logp = nd.log_softmax(flat_pred, axis=-1)
            ce = -nd.pick(logp, safe_tgt, axis=-1) * keep
            n_kept = nd.maximum(keep.sum(), nd.ones((1,)))
            cls_loss = ce.sum() / n_kept
            box_pred = box_flat
            n_pos = nd.maximum(loc_mask.sum() / 4.0, nd.ones((1,)))
            box_loss = (nd.smooth_l1(
                (box_pred - loc_target) * loc_mask, scalar=1.0)).sum() \
                / n_pos
            loss = cls_loss + box_loss
        loss.backward()
        trainer.step(B)
        losses.append(float(loss.asnumpy()))
        if ep % 10 == 0:
            log("epoch %d loss %.4f" % (ep, losses[-1]))
    return net, losses


def _ssd_loss(net, x, labels, sizes, ratios):
    feat, cls, box = net(x)
    B = x.shape[0]
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios)
    anchors = anchors.reshape(1, -1, 4)
    A = anchors.shape[1]
    cls_pred = nd.transpose(cls, axes=(0, 2, 3, 1)).reshape(B, A, 2)
    cls_pred_t = nd.transpose(cls_pred, axes=(0, 2, 1))
    box_flat = nd.transpose(box, axes=(0, 2, 3, 1)).reshape(B, -1)
    loc_target, loc_mask, cls_target = nd.contrib.MultiBoxTarget(
        anchors, labels, cls_pred_t, overlap_threshold=0.5,
        negative_mining_ratio=3.0, negative_mining_thresh=0.5)
    flat_pred = cls_pred.reshape(-1, 2)
    flat_tgt = cls_target.reshape(-1)
    keep = flat_tgt >= 0
    safe_tgt = nd.where(keep, flat_tgt, nd.zeros_like(flat_tgt))
    logp = nd.log_softmax(flat_pred, axis=-1)
    ce = -nd.pick(logp, safe_tgt, axis=-1) * keep
    n_kept = nd.maximum(keep.sum(), nd.ones((1,)))
    cls_loss = ce.sum() / n_kept
    n_pos = nd.maximum(loc_mask.sum() / 4.0, nd.ones((1,)))
    box_loss = (nd.smooth_l1((box_flat - loc_target) * loc_mask,
                             scalar=1.0)).sum() / n_pos
    return cls_loss + box_loss


def train_from_rec(rec_dir, epochs=12, log=print):
    """Train TinySSD from a .rec through ImageDetIter — the VERDICT
    criterion: the detection component the example exercises IS the
    real data pipeline (crop/pad/flip with consistent labels)."""
    rec, idx = make_rec_dataset(os.path.join(rec_dir, "ssd_synth.rec"))
    it = make_det_iter(rec, idx)
    net = TinySSD()
    net.initialize()
    first = next(iter(it))
    net(first.data[0])  # shape init
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    sizes, ratios = (0.3, 0.45), (1.0, 2.0, 0.5)
    epoch_losses = []
    for ep in range(epochs):
        it.reset()
        total, nb = 0.0, 0
        for batch in it:
            x, labels = batch.data[0], batch.label[0]
            with autograd.record():
                loss = _ssd_loss(net, x, labels, sizes, ratios)
            loss.backward()
            trainer.step(x.shape[0])
            total += float(loss.asnumpy())
            nb += 1
        epoch_losses.append(total / nb)
        log("rec-epoch %d loss %.4f" % (ep, epoch_losses[-1]))
    return net, epoch_losses


def detect(net, x, sizes=(0.3, 0.45), ratios=(1.0, 2.0, 0.5)):
    """MultiBoxDetection decode path (ref: multibox_detection.cc)."""
    feat, cls, box = net(x)
    B = x.shape[0]
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios)
    anchors = anchors.reshape(1, -1, 4)
    A = anchors.shape[1]
    cls_pred = nd.transpose(cls, axes=(0, 2, 3, 1)).reshape(B, A, 2)
    cls_prob = nd.softmax(nd.transpose(cls_pred, axes=(0, 2, 1)), axis=1)
    box_flat = nd.transpose(box, axes=(0, 2, 3, 1)).reshape(B, -1)
    return nd.contrib.MultiBoxDetection(cls_prob, box_flat,
                                        anchors, nms_threshold=0.45)


if __name__ == "__main__":
    net, losses = train()
    print("loss %.4f -> %.4f" % (losses[0], losses[-1]))
    assert losses[-1] < losses[0] * 0.5, "SSD training did not converge"
    rng = onp.random.RandomState(99)
    x, labels = make_batch(rng, batch=2)
    dets = detect(net, x)
    print("detections:", dets.shape)

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        net2, rec_losses = train_from_rec(d)
    print("rec-pipeline loss %.4f -> %.4f" % (rec_losses[0],
                                              rec_losses[-1]))
    assert rec_losses[-1] < rec_losses[0] * 0.7, \
        "SSD .rec-pipeline training did not converge"
    print("SSD example OK")
