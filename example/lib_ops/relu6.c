/* Example external operator library: relu6 + scale2, host f32 kernels.
 *
 * Analog of the reference's external-library examples built against
 * include/mxnet/lib_api.h. Build and load:
 *
 *   gcc -shared -fPIC -O2 -I../../src relu6.c -o librelu6.so
 *   >>> import mxnet_tpu as mx
 *   >>> mx.lib_api.load("/abs/path/librelu6.so")
 *   >>> mx.nd.relu6(mx.nd.array([-1., 3., 9.]))   # [0, 3, 6]
 */
#include "../../src/lib_api.h"

int initialize(int version) {
  return version >= 10600; /* non-zero = compatible (lib_api.h contract) */
}

static const char* kNames[] = {"relu6", "scale2"};

int _opRegSize(void) { return 2; }

const char* _opRegName(int idx) { return kNames[idx]; }

static int64_t numel(const int64_t* shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

/* both ops are elementwise: output shape == first input shape */
int _opInferShape(int idx, int nin,
                  const int64_t* const* in_shapes, const int* in_ndims,
                  int64_t* out_shape, int* out_ndim) {
  (void)idx;
  if (nin < 1 || in_ndims[0] > 8) return 1;
  *out_ndim = in_ndims[0];
  for (int i = 0; i < in_ndims[0]; ++i) out_shape[i] = in_shapes[0][i];
  return 0;
}

int _opCompute(int idx, int nin,
               const float* const* inputs,
               const int64_t* const* in_shapes, const int* in_ndims,
               float* output, const int64_t* out_shape, int out_ndim) {
  (void)nin;
  int64_t n = numel(out_shape, out_ndim);
  (void)in_shapes; (void)in_ndims;
  const float* x = inputs[0];
  if (idx == 0) { /* relu6 */
    for (int64_t i = 0; i < n; ++i) {
      float v = x[i] < 0.f ? 0.f : x[i];
      output[i] = v > 6.f ? 6.f : v;
    }
    return 0;
  }
  if (idx == 1) { /* scale2 */
    for (int64_t i = 0; i < n; ++i) output[i] = 2.f * x[i];
    return 0;
  }
  return 1;
}
