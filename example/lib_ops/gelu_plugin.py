"""Example Python external operator library.

Python plugins register jax-traceable ops — first-class citizens that
compile, fuse, and differentiate like built-ins (unlike C plugins,
which are host-callback islands). Load with:

    >>> import mxnet_tpu as mx
    >>> mx.lib_api.load("/abs/path/gelu_plugin.py")
    >>> y = mx.nd.my_gelu(x)          # nd, sym, and gluon all see it
"""
import jax.numpy as jnp

from mxnet_tpu import lib_api


def _gelu_fwd(x):
    # tanh-approximation GELU, pure jnp: traces into XLA
    c = jnp.sqrt(jnp.asarray(2.0 / jnp.pi, x.dtype))
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x ** 3)))


def _gelu_bwd(residuals, g):
    (x,) = residuals
    c = jnp.sqrt(jnp.asarray(2.0 / jnp.pi, x.dtype))
    inner = c * (x + 0.044715 * x ** 3)
    t = jnp.tanh(inner)
    dinner = c * (1.0 + 3 * 0.044715 * x ** 2)
    dgelu = 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t ** 2) * dinner
    return (g * dgelu,)


def initialize(version):
    """lib_api.h contract: non-zero iff compatible with `version`."""
    if version < 10600:
        return 0
    lib_api.register_op("my_gelu", _gelu_fwd, backward=_gelu_bwd)
    # an op relying on jax autodiff (no explicit backward)
    lib_api.register_op("my_softplus2",
                        lambda x: 2.0 * jnp.logaddexp(x, 0.0))
    return 1
