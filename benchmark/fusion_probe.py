"""Does XLA:TPU fuse elementwise producers into dot/conv operand loads?

Decides the round-4 ResNet HBM strategy (VERDICT r3 item 1): if
`relu(x*s+b) @ W` compiles to the same bytes-accessed as `x @ W`, the
normalize+ReLU can ride the consumer's operand load and interior
activations never need a materialized normalized copy.  Compares
bytes-accessed and wall time for materialize-vs-inline variants of the
1x1-conv (as dot) and 3x3-conv cases at ResNet bottleneck shapes.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def measure(name, fn, *args, iters=20):
    c = jax.jit(fn).lower(*args).compile()
    ca = c.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    # Timing rides a fori_loop INSIDE one jit with a scalar data
    # dependency chained into the first operand — per-call dispatch over
    # the tunnel otherwise pipelines and lies (memory: tpu-bench-timing).
    # The chain adds one elementwise pass over args[0] per iter, constant
    # across variants; `bytes` above is the compiler-exact signal.

    @jax.jit
    def loop(x0, *rest):
        def body(_, x):
            y = fn(x, *rest)
            y0 = y[0] if isinstance(y, tuple) else y
            eps = (y0.ravel()[0] * 0).astype(x0.dtype)
            return x * (1 + eps)
        return jax.lax.fori_loop(0, iters, body, x0)

    jax.block_until_ready(loop(*args))  # compile + warm
    t0 = time.perf_counter()
    jax.block_until_ready(loop(*args))
    dt = (time.perf_counter() - t0) / iters
    print("%-34s bytes=%8.1f MB  flops=%6.2f G  t=%7.3f ms  eff_GBps=%.0f"
          % (name, ca.get("bytes accessed", 0) / 1e6,
             ca.get("flops", 0) / 1e9, dt * 1e3,
             ca.get("bytes accessed", 0) / dt / 1e9))
    return ca.get("bytes accessed", 0), dt


def main():
    rs = np.random.RandomState(0)
    B, H, W_, C, K = 128, 56, 56, 256, 64
    x = jnp.asarray(rs.rand(B * H * W_, C), jnp.bfloat16)
    w = jnp.asarray(rs.rand(C, K), jnp.bfloat16)
    s = jnp.asarray(rs.rand(C), jnp.bfloat16)
    b = jnp.asarray(rs.rand(C), jnp.bfloat16)

    print("== 1x1 conv as dot, [%d, %d] @ [%d, %d] ==" % (B * H * W_, C, C, K))
    measure("dot(x, w)", lambda x, w: x @ w, x, w)
    measure("dot(relu(x*s+b), w)",
            lambda x, w, s, b: jnp.maximum(x * s + b, 0) @ w, x, w, s, b)

    def two_step(x, w, s, b):
        y = jnp.maximum(x * s + b, 0)
        y = jax.lax.optimization_barrier(y)  # force materialization
        return y @ w
    measure("barrier(relu(x*s+b)) @ w", two_step, x, w, s, b)

    print("== 3x3 conv NHWC, [%d,%d,%d,%d] -> %d ==" % (B, H, W_, C, K))
    xc = jnp.asarray(rs.rand(B, H, W_, C), jnp.bfloat16)
    wc = jnp.asarray(rs.rand(3, 3, C, K), jnp.bfloat16)
    dn = jax.lax.conv_dimension_numbers(xc.shape, wc.shape,
                                        ("NHWC", "HWIO", "NHWC"))

    def conv(x, w):
        return jax.lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                            dimension_numbers=dn)
    measure("conv(x, w)", conv, xc, wc)
    measure("conv(relu(x*s+b), w)",
            lambda x, w, s, b: conv(jnp.maximum(x * s + b, 0), w),
            xc, wc, s, b)

    def conv2(x, w, s, b):
        y = jnp.maximum(x * s + b, 0)
        y = jax.lax.optimization_barrier(y)
        return conv(y, w)
    measure("conv(barrier(relu(x*s+b)), w)", conv2, xc, wc, s, b)

    # epilogue side: can a reduction (BN stats of the OUTPUT) fuse into
    # the conv/dot's result write?
    print("== epilogue stat fusion ==")
    def dot_stats(x, w):
        y = x @ w
        yf = y.astype(jnp.float32)
        return y, jnp.mean(yf, 0), jnp.mean(yf * yf, 0)
    measure("dot + out stats", dot_stats, x, w)

    def conv_stats(x, w):
        y = conv(x, w)
        yf = y.astype(jnp.float32)
        return y, jnp.mean(yf, (0, 1, 2)), jnp.mean(yf * yf, (0, 1, 2))
    measure("conv + out stats", conv_stats, xc, wc)


if __name__ == "__main__":
    main()
