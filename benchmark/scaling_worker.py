"""One rank of the multi-process data-parallel scaling benchmark
(VERDICT r3 item 2 — the analog of the reference's 1..256-GPU scaling
table, example/image-classification/README.md:309).

Launched by benchmark/scaling.py via tools/launch.py:

    python tools/launch.py -n 4 python benchmark/scaling_worker.py

Each rank trains thumbnail ResNet-18 through the Gluon Trainer with
kvstore=dist_device_sync (gradients allreduced over the jax.distributed
Gloo/ICI backend — sync semantics, every step sees all ranks). Rank 0
appends one JSON line with the measured global img/s to the path in
MXTPU_SCALING_OUT.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

_COORD = os.environ.get("MXTPU_COORDINATOR")
if _COORD and int(os.environ.get("MXTPU_NUM_PROCS", "1")) > 1:
    jax.distributed.initialize(_COORD,
                               int(os.environ["MXTPU_NUM_PROCS"]),
                               int(os.environ["MXTPU_PROC_ID"]))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1  # noqa: E402


def main():
    batch = int(os.environ.get("MXTPU_SCALING_BATCH", "16"))
    steps = int(os.environ.get("MXTPU_SCALING_STEPS", "8"))
    warmup = int(os.environ.get("MXTPU_SCALING_WARMUP", "2"))
    nproc = int(os.environ.get("MXTPU_NUM_PROCS", "1"))
    rank = int(os.environ.get("MXTPU_PROC_ID", "0"))

    net = resnet18_v1(classes=10, thumbnail=True)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(np.zeros((1, 3, 32, 32), "f")))  # deferred init
    net.hybridize()

    kv = "dist_device_sync" if nproc > 1 else "device"
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rs = np.random.RandomState(1000 + rank)
    x = mx.nd.array(rs.rand(batch, 3, 32, 32).astype("f"))
    y = mx.nd.array(rs.randint(0, 10, (batch,)).astype("f"))

    def step():
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(batch)
        return loss

    for _ in range(warmup):
        float(step().asnumpy().sum())
    t0 = time.perf_counter()
    loss = None
    for _ in range(steps):
        loss = step()
    float(loss.asnumpy().sum())  # sync
    dt = time.perf_counter() - t0

    global_imgs_per_sec = batch * nproc * steps / dt
    if rank == 0:
        out_path = os.environ.get("MXTPU_SCALING_OUT")
        rec = {"n": nproc, "batch_per_rank": batch, "steps": steps,
               "imgs_per_sec": round(global_imgs_per_sec, 2),
               "step_ms": round(dt / steps * 1e3, 2)}
        if out_path:
            with open(out_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
