"""One process of the 2-process GSPMD fused-step proof (ISSUE 16): the
Trainer-path ``FusedTrainStep`` compiles and runs its dp×tp×sp GSPMD
program over a MULTI-PROCESS mesh, not just the single-process
8-device one.

Each process owns 4 virtual CPU devices; jax.distributed stitches them
into one 8-device dp=2×tp=2×sp=2 global mesh. Both ranks feed the SAME
deterministic batches to a gluon net + Trainer fused step with explicit
tensor-parallel rules, take 4 steps (eager warming → compile → fused
hit), and print the final loss — the launching test asserts the two
ranks' losses agree exactly and that the step reports mode 'fused' with
the matched-shardings contract held. Launched by tools/launch.py -n 2
(see tests/test_dist.py).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

from tools.launch import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(4)

jax.distributed.initialize(os.environ["MXTPU_COORDINATOR"],
                           int(os.environ["MXTPU_NUM_PROCS"]),
                           int(os.environ["MXTPU_PROC_ID"]))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.parallel import create_mesh  # noqa: E402
from mxnet_tpu.parallel import sharding as psh  # noqa: E402


def main():
    rank = int(os.environ["MXTPU_PROC_ID"])
    devs = jax.devices()
    assert len(devs) == 8, \
        "expected 8 global devices (2 procs x 4), got %d" % len(devs)

    rs = np.random.RandomState(0)  # identical on both ranks
    w1 = rs.randn(16, 12).astype(np.float32) * 0.1
    w2 = rs.randn(4, 16).astype(np.float32) * 0.1
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=12, prefix="d0_"))
    net.add(nn.Dense(4, in_units=16, prefix="d1_"))
    net.initialize()
    net.hybridize()
    params = dict(net.collect_params())
    for name, p in params.items():
        if p.shape == (16, 12):
            p.set_data(mx.nd.array(w1))
        elif p.shape == (4, 16):
            p.set_data(mx.nd.array(w2))
        else:
            p.set_data(mx.nd.array(np.zeros(p.shape, np.float32)))

    mesh = create_mesh(devices=devs, dp=2, tp=2, sp=2)
    loss_fn = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.fuse_step(lambda xx, yy: loss_fn(net(xx), yy), mesh=mesh,
                        bucket_bytes=512,
                        rules=[(r"d0.*weight$", ("tp", None)),
                               (r"d1.*weight$", (None, "tp"))])
    assert step._gspmd_mode(), "model axes must select the GSPMD form"

    data = np.random.RandomState(7)  # identical batches on both ranks
    loss = None
    for _ in range(4):
        x = mx.nd.array(data.rand(8, 12).astype(np.float32))
        y = mx.nd.array(data.rand(8, 4).astype(np.float32))
        loss = step(x, y, batch_size=8)
    assert step.last_mode == "fused", step.last_mode
    assert step.matched_step_shardings() is True
    # the loss output is pinned replicated, so every rank holds the
    # whole value; host_array stages it through the addressable shard
    val = float(np.asarray(psh.host_array(loss._data)).mean())
    assert np.isfinite(val), val
    print("gspmd fused step rank %d: dp=2 tp=2 sp=2 over 2 procs ok, "
          "loss=%.8f" % (rank, val))


if __name__ == "__main__":
    main()
