"""One process of the 2-process x 4-device multichip dryrun
(VERDICT r3 item 2: validate the MULTI-PROCESS sharded path, not just
the single-process 8-device mesh).

Each process owns 4 virtual CPU devices; jax.distributed stitches them
into one 8-device global mesh; the full GSPMD transformer train step
(dp=4 x sp=2, ring attention, chunked CE) jits over it and runs one
step. Launched by __graft_entry__.dryrun_multichip (phase 6) or by
tools/launch.py -n 2.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

from tools.launch import force_virtual_cpu_devices  # noqa: E402

# Survive a preloaded accelerator plugin that already grabbed a backend
# at interpreter startup (the r4 MULTICHIP regression); see the helper's
# docstring. Must precede jax.distributed.initialize.
force_virtual_cpu_devices(4)

jax.distributed.initialize(os.environ["MXTPU_COORDINATOR"],
                           int(os.environ["MXTPU_NUM_PROCS"]),
                           int(os.environ["MXTPU_PROC_ID"]))

import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from mxnet_tpu.parallel import create_mesh  # noqa: E402
from mxnet_tpu.parallel import transformer as T  # noqa: E402


def main():
    rank = int(os.environ["MXTPU_PROC_ID"])
    devs = jax.devices()
    assert len(devs) == 8, \
        "expected 8 global devices (2 procs x 4), got %d" % len(devs)
    assert len(jax.local_devices()) == 4, \
        "expected 4 local devices, got %d" % len(jax.local_devices())

    mesh = create_mesh(devices=devs, dp=4, sp=2)
    cfg = T.TransformerConfig(vocab_size=64, dim=16, n_layers=2,
                              n_heads=4, ffn_hidden=32, attn_mode="ring",
                              loss_chunks=4)
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        batch_sh = NamedSharding(mesh.mesh, P("dp", "sp"))
        gen = jax.jit(
            lambda k: jr.randint(k, (8, 16), 0, cfg.vocab_size,
                                 dtype=jnp.int32),
            out_shardings=batch_sh)
        toks = gen(jr.PRNGKey(1))
        tgts = gen(jr.PRNGKey(2))
        state, loss = step_fn(state, toks, tgts)
        val = float(loss)  # replicated scalar: addressable everywhere
    assert val == val and val > 0, val
    print("multiproc dryrun rank %d: dp=4 sp=2 over 2 procs ok, "
          "loss=%.4f" % (rank, val))


if __name__ == "__main__":
    main()
