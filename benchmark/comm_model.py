"""Communication decomposition + scaling projection (VERDICT r4 #2).

The r1-r4 SCALING_r*.json measured multiprocess wall-clock on a ONE-core
CI host, where n ranks timeshare one core — an efficiency number that
says nothing about hardware scaling. This harness replaces it with what
IS measurable here, plus a clearly-labeled model for what is not:

1. MEASURED (virtual 8-device mesh, compiled HLO): per-step collective
   payload bytes by kind (all-reduce / all-gather / reduce-scatter /
   collective-permute / all-to-all) and per-step FLOPs, for three
   sharded train-step configs (pure dp, dp x tp, dp x tp x sp). These
   come from the SPMD partitioner's actual output, not hand counting.
2. VALIDATED: the analytic gradient-all-reduce payload (4 bytes/param)
   is checked against the HLO measurement on the pure-dp config; the
   model is only trusted because this delta is small.
3. PROJECTED: ring-all-reduce step efficiency at n = 8..256 chips for
   the two real single-chip workloads whose step times were measured on
   the attached v5e (bench.py), under stated ICI/DCN bandwidth
   assumptions — against the reference's published 90.1% at 256 GPUs
   (ref: example/image-classification/README.md:309-319).

    python benchmark/comm_model.py --out SCALING_r05.json
(CPU env: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

# one HLO result type, e.g. f32[512,128]{1,0} or bf16[] or (…, …)
_SHAPE_RE = re.compile(r"(%s)\[([\d,]*)\]" % "|".join(_DTYPE_BYTES))


def _shape_bytes(type_str):
    """Total bytes of an HLO result type (handles tuples)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _tuple_elements(type_str):
    """Top-level elements of a tuple type ``(a, b, ...)``; [] when the
    type is not a tuple. Layout braces (``{1,0}``) nest commas, so the
    split tracks depth across (), [] and {}."""
    s = type_str.strip()
    if not s.startswith("("):
        return []
    depth, start, elems = 0, 1, []
    for i, ch in enumerate(s):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
            if depth == 0:
                elems.append(s[start:i])
                break
        elif ch == "," and depth == 1:
            elems.append(s[start:i])
            start = i + 1
    return elems


def _split_computations(hlo_text):
    """{computation_name: [lines]} for every computation block."""
    comps = {}
    name, buf, depth = None, [], 0
    for line in hlo_text.splitlines():
        if name is None:
            m = re.match(r"(?:ENTRY )?%?([\w.\-]+)(?:\.clone)? \(.*\{",
                         line)
            if m:
                name = m.group(1)
                buf = [line]
                depth = line.count("{") - line.count("}")
                if depth <= 0:
                    comps[name] = buf
                    name = None
            continue
        buf.append(line)
        depth += line.count("{") - line.count("}")
        if depth <= 0:
            comps[name] = buf
            name = None
    return comps


def _trip_count(cond_lines):
    """Trip count of a canonical jax-scan while loop. The bound is the
    scalar integer constant the condition compares the induction
    variable against; post-optimization the compare itself often hides
    inside a wrapped_compare fusion, so: exactly one scalar int
    constant in the condition computation => that is the bound. None
    when the bound is loop-carried (caller falls back)."""
    consts = [int(m.group(1)) for line in cond_lines
              for m in [re.search(
                  r"= [su]\d+\[\] constant\((\d+)\)", line)] if m]
    return consts[0] if len(consts) == 1 else None


def _is_degenerate_groups(line):
    """True when the collective's replica_groups are singletons — a
    one-member group exchanges nothing, so the op is sharding
    bookkeeping, not wire traffic (r07 fix: the shard_map'd loss emits
    one such no-op AR per layer-stack leaf, which inflated the measured
    payload by a full parameter's worth of phantom bytes)."""
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[", line)
    if m:
        return int(m.group(2)) == 1   # iota form: [groups, per_group]
    m = re.search(r"replica_groups=\{(\{[^}]*\})", line)
    if m:
        return "," not in m.group(1)  # literal form: first group {n}
    return False


def hlo_collective_bytes(hlo_text):
    """Per-kind collective payload bytes for ONE step, loop-aware: a
    collective inside a `while` body (jax.lax.scan over layers / loss
    chunks) executes trip-count times, so body bytes are multiplied by
    the trip count parsed from the loop condition (r5 fix: the static
    count under-reported by exactly (L-1) layers' gradients).
    Degenerate collectives (singleton replica groups) are skipped —
    they move zero bytes.

    Returns (bytes_by_kind, counts_by_kind, n_unresolved_loops)."""
    comps = _split_computations(hlo_text)
    coll_re = re.compile(r"=\s+(\(.*?\)|\S+)\s+(%s)(-start)?\("
                         % "|".join(_COLLECTIVES))
    while_re = re.compile(
        r"while\(.*condition=%([\w.\-]+), body=%([\w.\-]+)")
    unresolved = [0]

    def bytes_of(comp_name, seen):
        out = {k: 0 for k in _COLLECTIVES}
        counts = {k: 0 for k in _COLLECTIVES}
        if comp_name not in comps or comp_name in seen:
            return out, counts
        for line in comps[comp_name]:
            m = coll_re.search(line)
            if m and "-done" not in line.split("=", 1)[1][:60] \
                    and not _is_degenerate_groups(line):
                ty = m.group(1)
                if m.group(3):
                    # async form: the -start result type is a tuple of
                    # (operand, result[, context...]) — e.g. a
                    # collective-permute-start carries two trailing
                    # u32[] context elements. Summing the whole tuple
                    # double-counts the payload, so keep only the
                    # result element, always the second (the -done
                    # side is already skipped)
                    elems = _tuple_elements(ty)
                    if len(elems) >= 2:
                        ty = elems[1]
                out[m.group(2)] += _shape_bytes(ty)
                counts[m.group(2)] += 1
            w = while_re.search(line)
            if w:
                cond, body = w.groups()
                trips = _trip_count(comps.get(cond, []))
                sub, subc = bytes_of(body, seen | {comp_name})
                if any(sub.values()) and trips is None:
                    unresolved[0] += 1
                    trips = 1
                for k in _COLLECTIVES:
                    out[k] += (trips or 1) * sub[k]
                    counts[k] += (trips or 1) * subc[k]
        return out, counts

    entry = None
    for line in hlo_text.splitlines():
        m = re.match(r"ENTRY %?([\w.\-]+)", line)
        if m:
            entry = m.group(1)
            break
    out, counts = bytes_of(entry, frozenset())
    return out, counts, unresolved[0]


def collect_hlo_inventory(program):
    """The one choke point for compiled-program collective inventory:
    accepts a compiled executable (anything with ``as_text()``) or raw
    HLO text and returns the per-kind payload decomposition every
    consumer reads the same way — bench gates, the fused-step compile
    attribution, and hlolint H002 (which diffs it against the analytic
    plan). Returns ``{"bytes_by_kind", "counts_by_kind",
    "unresolved_loops", "total_bytes"}``."""
    txt = program if isinstance(program, str) \
        else program.as_text()
    by_kind, counts, unresolved = hlo_collective_bytes(txt or "")
    return {
        "bytes_by_kind": by_kind,
        "counts_by_kind": counts,
        "unresolved_loops": unresolved,
        "total_bytes": sum(by_kind.values()),
    }


def measure_config(name, mesh_axes, cfg_kwargs, B, S):
    """Compile one sharded train step on the virtual mesh; return the
    collective decomposition + cost-analysis FLOPs."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    from mxnet_tpu.parallel import create_mesh
    from mxnet_tpu.parallel import transformer as T

    mesh = create_mesh(devices=jax.devices()[:8], **mesh_axes)
    cfg = T.TransformerConfig(**cfg_kwargs)
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        toks = jnp.zeros((B, S), jnp.int32)
        compiled = step_fn.lower(state, toks, toks).compile()
    inv = collect_hlo_inventory(compiled)
    by_kind, counts, unresolved = (inv["bytes_by_kind"],
                                   inv["counts_by_kind"],
                                   inv["unresolved_loops"])
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost
    n_params = sum(int(jnp.size(p))
                   for p in jax.tree_util.tree_leaves(state[0]))
    return {
        "config": name,
        "mesh": mesh_axes,
        "params": n_params,
        "batch": B, "seq": S,
        "flops_per_step": float(cost.get("flops", 0.0)) if cost else None,
        "collective_payload_bytes": by_kind,
        "collective_counts": counts,
        "unresolved_loops": unresolved,
    }


# -- the projection model ---------------------------------------------------

# Public per-chip numbers for TPU v5e, stated as model ASSUMPTIONS
# (zero-egress environment; values from the public v5e datasheet and
# the jax-ml scaling book): bf16 peak 197 TF/s; 4 ICI links/chip at
# ~45 GB/s each way -> ~180 GB/s aggregate per chip; DCN ~25 GB/s per
# 8-chip host. Ring all-reduce moves 2(n-1)/n x payload per chip.
ASSUMPTIONS = {
    "chip": "TPU v5e",
    "bf16_peak_tflops": 197.0,
    # Peak matmul throughput by dominant program dtype. bf16 is the
    # datasheet number; f32 runs the MXU at half rate; int8 doubles it
    # (the PR 9 quantized_matmul path is what actually hits this peak —
    # its epilogue-fused dequant keeps the 2x from being eaten by
    # casts). f16 aliases bf16 (same MXU rate on this part).
    "peak_tflops": {
        "bf16": 197.0,
        "f16": 197.0,
        "f32": 98.5,
        "int8": 394.0,
    },
    "hbm_bw_GBps": 819.0,
    "ici_bw_per_chip_GBps": 180.0,
    "dcn_bw_per_host_GBps": 25.0,
    "chips_per_host": 8,
    "allreduce_algorithm": "ring, wire bytes = 2(n-1)/n * payload",
    "overlap": "both bounds reported: none (serial) and full "
               "(comm hidden under compute)",
}


def peak_tflops(dtype="bf16"):
    """Peak TFLOP/s for a program whose dominant dtype is ``dtype``
    (a short key: ``bf16``/``f16``/``f32``/``int8``). Unknown dtypes
    fall back to the bf16 peak — the conservative default the modeled
    compute time has always used."""
    table = ASSUMPTIONS["peak_tflops"]
    return table.get(str(dtype), table["bf16"])


def allreduce_seconds(payload_bytes, n):
    """(t_ici, t_dcn) seconds to ring-all-reduce one payload at n
    chips under ASSUMPTIONS: 2(n-1)/n x payload over per-chip ICI, plus
    the hierarchical DCN term for multi-host (payload re-reduced across
    hosts at host DCN bandwidth). The single place the wire-time
    formula lives — `project` and bench.py's comm_overlap gate both
    price collectives through it."""
    ici = ASSUMPTIONS["ici_bw_per_chip_GBps"] * 1e9
    dcn = ASSUMPTIONS["dcn_bw_per_host_GBps"] * 1e9
    per_host = ASSUMPTIONS["chips_per_host"]
    t_ici = 2.0 * (n - 1) / n * payload_bytes / ici
    hosts = max(1, n // per_host)
    t_dcn = (2.0 * (hosts - 1) / hosts * payload_bytes / dcn
             if hosts > 1 else 0.0)
    return t_ici, t_dcn


def project(step_time_s, grad_payload_bytes, ns):
    """Ring-all-reduce efficiency at n chips over ICI, plus the
    hierarchical DCN term for multi-host (payload re-reduced across
    hosts at host DCN bandwidth)."""
    rows = []
    for n in ns:
        t_ici, t_dcn = allreduce_seconds(grad_payload_bytes, n)
        t_comm = t_ici + t_dcn
        rows.append({
            "n": n,
            "comm_ms": round(t_comm * 1e3, 2),
            "ici_ms": round(t_ici * 1e3, 2),
            "dcn_ms": round(t_dcn * 1e3, 2),
            "efficiency_no_overlap": round(
                step_time_s / (step_time_s + t_comm), 4),
            "efficiency_full_overlap": round(
                min(1.0, step_time_s / max(step_time_s, t_comm)), 4),
        })
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    V, D, L = 512, 128, 2
    small = dict(vocab_size=V, dim=D, n_layers=L, n_heads=4,
                 ffn_hidden=4 * D, attn_mode="local", loss_chunks=4)
    measured = [
        measure_config("pure_dp", {"dp": 8}, small, B=16, S=64),
        measure_config("dp_x_tp", {"dp": 4, "tp": 2}, small, B=16, S=64),
        measure_config("dp_tp_sp", {"dp": 2, "tp": 2, "sp": 2},
                       dict(small, attn_mode="ring"), B=16, S=64),
    ]

    # validation: pure-dp grad all-reduce payload vs the analytic
    # model. The naive 4-bytes/param model is WRONG in an instructive
    # way the HLO exposed: the chunked-CE scan all-reduces the
    # unembedding gradient once PER CHUNK (XLA keeps the AR inside the
    # loop), so dynamic payload = params + (chunks-1) * vocab*dim
    # (+ the scalar loss). This decomposition reproduces the measured
    # bytes exactly and is itself the r5 finding: chunked CE trades
    # HBM for (loss_chunks-1) extra unembedding-grad reductions.
    dp = measured[0]
    chunks, vocab, dim = small["loss_chunks"], small["vocab_size"], \
        small["dim"]
    analytic = 4 * (dp["params"] + (chunks - 1) * vocab * dim + 1)
    got = dp["collective_payload_bytes"]["all-reduce"]
    delta = abs(got - analytic) / analytic
    validation = {
        "analytic_model": "4B * (params + (loss_chunks-1)*vocab*dim "
                          "+ loss_scalar)",
        "analytic_grad_allreduce_bytes": analytic,
        "hlo_measured_allreduce_bytes": got,
        "rel_delta": round(delta, 6),
        "model_trusted": bool(delta < 0.05),
        "naive_4B_per_param_bytes": 4 * dp["params"],
        "finding": (
            "chunked-CE re-all-reduces the unembedding grad per chunk "
            "(+(chunks-1)*vocab*dim*4 bytes/step). Root cause isolated "
            "(r5): GSPMD keeps the AR inside ANY scan that accumulates "
            "a batch-sharded contraction — scan carries must hold a "
            "concrete sharding, so each iteration's partial sum is "
            "reduced before the add; reproduced with a 10-line minimal "
            "scan, and a hand-written custom-vjp accumulation compiles "
            "to the same HLO. Fixing it needs Explicit-mode "
            "PartitionSpec(unreduced=...) shardings (rejected: "
            "framework-wide mesh-mode migration) or a shard_map'd loss "
            "mirroring every dp x tp x sp layout by hand. Documented "
            "cost, not a bug: single-chip perf is unaffected."),
    }

    # projections for the two REAL single-chip workloads (step times
    # measured on the attached v5e by bench.py; BENCH_r04/r05). The
    # transformer is projected under BOTH gradient-payload patterns:
    # the observed XLA lowering (chunked CE re-reduces the 131M-param
    # unembedding grad each of the 8 chunks) and the ideal
    # one-AR-per-param pattern the finding above would restore.
    ns = [8, 16, 32, 64, 128, 256]
    t_params = 1_604_400_000
    t_unembed = 32000 * 4096
    t_ideal = 4 * t_params
    t_observed = 4 * (t_params + 7 * t_unembed)
    projections = {
        "resnet50_b128_bf16": {
            "measured_step_s": 0.0495,  # 2586 img/s at b128 (BENCH_r04)
            "grad_payload_bytes": 4 * 25_557_032,
            "rows": project(0.0495, 4 * 25_557_032, ns),
        },
        "transformer_1p6B_b12_s2048": {
            "measured_step_s": 1.909,  # 12,869 tok/s at b12 x s2048
            "grad_payload_bytes": t_ideal,
            "rows": project(1.909, t_ideal, ns),
        },
        "transformer_1p6B_b12_s2048_observed_chunked_ce": {
            "measured_step_s": 1.909,
            "grad_payload_bytes": t_observed,
            "rows": project(1.909, t_observed, ns),
        },
    }

    out = {
        "metric": "comm_decomposition_scaling_model",
        "platform": "virtual 8-device cpu mesh (HLO measurement) + "
                    "one real v5e (step times)",
        "measured": measured,
        "validation": validation,
        "assumptions": ASSUMPTIONS,
        "projection": projections,
        "reference_bar": {
            "n": 256, "efficiency": 0.901,
            "source": "ref example/image-classification/README.md:309 "
                      "(dist_sync, 256 GPUs)",
        },
        "conclusion": (
            "At 256 v5e chips the ResNet-50 grad all-reduce costs "
            "9.1ms (1.1ms ICI + 7.9ms cross-host DCN) against a "
            "49.5ms measured step: 84.5% efficiency with ZERO "
            "overlap, ~100% once the reduction overlaps the backward "
            "pass (standard, and what the reference's own 90.1% "
            "already assumes) — DCN, not ICI, is the binding term. "
            "The transformer's exposure is larger (6.4GB f32 grads) "
            "but still fully hideable under its 1.9s step. The "
            "measurable risk is the chunked-CE AR-per-chunk pattern "
            "(validation.finding): at 256 chips it adds 36% to the "
            "transformer wire bytes unless the unembedding grad is "
            "accumulated locally first."),
    }
    js = json.dumps(out)
    print(js)
    if args.out:
        with open(args.out, "w") as f:
            f.write(js + "\n")
    return out


if __name__ == "__main__":
    main()
