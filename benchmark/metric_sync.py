"""Measure the device-side metric accumulation win (VERDICT r4 item 6).

The reference's metrics call `.asnumpy()` per batch (ref:
python/mxnet/metric.py Accuracy.update), forcing one device->host sync
per batch per metric; mxnet_tpu/metric.py instead accumulates a lazy
device scalar and syncs only inside get(). This harness times an
eval-style loop (jitted forward + Accuracy update every batch, one
get() at the end) both ways on the attached device and prints one JSON
line with the per-step times and the speedup.

Run on the real chip (default env) or CPU:
    python benchmark/metric_sync.py
"""
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class _HostAccuracy:
    """The reference's accumulation pattern, verbatim-in-spirit: pull
    the batch to the host, reduce with numpy, add into Python floats."""

    def __init__(self):
        self.hits = 0
        self.seen = 0

    def update(self, label, pred):
        p = onp.argmax(pred.asnumpy(), axis=1)
        l_ = label.asnumpy().astype("int32")
        self.hits += int((p == l_).sum())
        self.seen += l_.size

    def get(self):
        return self.hits / max(self.seen, 1)


def main(batches=100, batch=256, dim=1024, classes=100):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import metric as mxmetric

    dev = jax.devices()[0]
    rs = onp.random.RandomState(0)
    w1 = jax.device_put(rs.rand(dim, dim).astype("float32") * 0.02, dev)
    w2 = jax.device_put(rs.rand(dim, classes).astype("float32") * 0.02,
                        dev)
    x = jax.device_put(rs.rand(batch, dim).astype("float32"), dev)
    labels = jax.device_put(
        rs.randint(0, classes, (batch,)).astype("float32"), dev)

    @jax.jit
    def forward(x, step):
        # a step-dependent perturbation so XLA cannot hoist the body
        h = jnp.maximum(x + step * 1e-6, 0.0) @ w1
        return jnp.maximum(h, 0.0) @ w2

    label_nd = mx.nd.NDArray(labels)

    def timed_loop(update, read):
        forward(x, 0.0).block_until_ready()  # compile outside the clock
        t0 = time.time()
        for i in range(batches):
            update(label_nd, mx.nd.NDArray(forward(x, float(i))))
        value = read()  # for the device path: the ONLY sync in the loop
        return value, time.time() - t0

    dev_metric = mxmetric.Accuracy()
    v_dev, t_dev = timed_loop(
        lambda l, p: dev_metric.update([l], [p]),
        lambda: dev_metric.get()[1])

    host_metric = _HostAccuracy()
    v_host, t_host = timed_loop(host_metric.update, host_metric.get)

    assert abs(v_dev - v_host) < 1e-6, (v_dev, v_host)
    out = {
        "metric": "metric_eval_step_time",
        "platform": jax.devices()[0].platform,
        "batches": batches,
        "device_accum_ms_per_step": round(t_dev / batches * 1e3, 3),
        "host_sync_ms_per_step": round(t_host / batches * 1e3, 3),
        "speedup": round(t_host / t_dev, 2),
        "accuracy_checked_equal": True,
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
