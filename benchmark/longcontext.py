"""Long-context single-chip training sweep.

SURVEY makes long-context first-class; this harness measures how far
ONE chip's HBM stretches with the Pallas flash kernels (fwd + flash-2
backward), chunked CE, and full per-layer remat — the single-chip
anchor of the sequence-scaling story (ring/Ulysses over the mesh extend
it across chips; tests/test_parallel.py proves those paths compile and
match dense).

Emits one JSON line per configuration:
  {"dim": D, "layers": L, "seq": S, "params_m": M,
   "tokens_per_sec": T, "model_tflops_per_sec": F, "final_loss": ...}

Measured on one v5e (16 GB), bf16 (recorded in LONGCONTEXT_r04.json):
  668M  at seq 16,384: 15,745 tok/s (63.1 TF/s)
  668M  at seq 32,768: 11,082 tok/s (44.4 TF/s)
  668M  at seq 65,536:  6,885 tok/s (27.6 TF/s)
  1.42B at seq 32,768:  5,679 tok/s (48.5 TF/s)
The TF/s decline with S is the attention share growing (score FLOPs
scale with S^2 while the flash kernel runs below matmul rate — see
docs/ROADMAP.md transformer MFU study); tokens/s stays usable to 64k.

Usage: python benchmark/longcontext.py [--configs dim,layers,seq ...]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_CONFIGS = [(2048, 8, 16384), (2048, 8, 32768), (2048, 8, 65536),
                   (2560, 12, 32768)]


def run(dim, layers, seq, batch=1, iters=3):
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.parallel import create_mesh
    from mxnet_tpu.parallel import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=32000, dim=dim, n_layers=layers,
        n_heads=max(1, dim // 128),
        ffn_hidden=dim * 4, max_seq_len=seq, dtype="bfloat16",
        attn_mode="local",
        # chunked CE: [B,S,32k] logits never materialize — mandatory at
        # these sequence lengths
        loss_chunks=max(8, seq // 2048))
    mesh = create_mesh(devices=jax.devices()[:1], dp=1)
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    rs = np.random.RandomState(0)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)),
                           jnp.int32)
        # independent targets — same convention as bench.py's
        # transformer bench (targets == inputs would let causal
        # attention copy-predict and collapse the loss)
        tgts = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)),
                           jnp.int32)
        state, loss = step_fn(state, toks, tgts)
        float(loss)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step_fn(state, toks, tgts)
        loss = float(loss)
        dt = (time.perf_counter() - t0) / iters
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(state[0]))
    return {
        "dim": dim, "layers": layers, "seq": seq, "batch": batch,
        "params_m": round(n_params / 1e6, 1),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "model_tflops_per_sec": round(
            6 * n_params * batch * seq / dt / 1e12, 1),
        "final_loss": round(loss, 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="*",
                    help="dim,layers,seq triples (default: the sweep)")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    for raw in (args.configs or
                ["%d,%d,%d" % c for c in DEFAULT_CONFIGS]):
        try:
            dim, layers, seq = (int(x) for x in raw.split(","))
            print(json.dumps(run(dim, layers, seq, iters=args.iters)),
                  flush=True)
        except Exception as e:  # noqa: BLE001 — an OOM or malformed
            # config must not kill the remaining sweep
            print(json.dumps({"config": raw, "error": str(e)[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
