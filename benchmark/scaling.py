"""Multi-process data-parallel scaling benchmark driver
(VERDICT r3 item 2).

Measures global img/s of sync data-parallel thumbnail-ResNet training at
n = 1, 2, 4, 8 local processes (tools/launch.py + dist_device_sync) and
writes a SCALING_r*.json with per-n throughput and efficiency vs n=1 —
the CI-shaped analog of the reference's 1..256-GPU scaling table
(ref: example/image-classification/README.md:309-319, 90.1% at 256).

On a real multi-host TPU slice the same harness measures ICI/DCN
scaling; on a CI host the curve measures launcher + Gloo-collective +
oversubscription overhead (a 1-core host runs all ranks on one core, so
compute does NOT scale — efficiency there reflects harness sanity, not
hardware).

    python benchmark/scaling.py --ns 1,2,4,8 --out SCALING_r04.json
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.launch import launch_local  # noqa: E402


def run_one(n, batch, steps, out_path):
    env = {
        "JAX_PLATFORMS": "cpu",
        "MXTPU_SCALING_OUT": out_path,
        "MXTPU_SCALING_BATCH": str(batch),
        "MXTPU_SCALING_STEPS": str(steps),
    }
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scaling_worker.py")
    codes = launch_local(n, [sys.executable, worker], env_extra=env)
    if any(codes):
        raise RuntimeError("n=%d run failed: %s" % (n, codes))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ns", default="1,2,4,8")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="write the summary JSON here")
    args = ap.parse_args(argv)

    results = []
    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, "recs.jsonl")
        for n in [int(x) for x in args.ns.split(",")]:
            run_one(n, args.batch, args.steps, rec_path)
        with open(rec_path) as f:
            results = [json.loads(ln) for ln in f if ln.strip()]

    base = next((r for r in results if r["n"] == 1), results[0])
    for r in results:
        ideal = base["imgs_per_sec"] * r["n"] / base["n"]
        r["efficiency"] = round(r["imgs_per_sec"] / ideal, 3)

    summary = {
        "metric": "dist_device_sync_scaling",
        "model": "resnet18_thumbnail_32x32",
        "host_cpus": os.cpu_count(),
        "platform": "cpu-mesh",
        "note": ("sync dp over jax.distributed collectives; on a "
                 "1-core host all ranks share one core so efficiency "
                 "measures harness overhead, not hardware scaling"),
        "points": results,
    }
    line = json.dumps(summary)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print("\n  n  imgs/s   step_ms  efficiency", file=sys.stderr)
    for r in results:
        print("%3d  %7.1f  %7.1f  %9.3f"
              % (r["n"], r["imgs_per_sec"], r["step_ms"],
                 r["efficiency"]), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
