"""On-device numerics sweep (VERDICT r3 item 8).

The CPU test suite never touches the real chip; this harness runs N
representative ops per family on the attached device and compares
against goldens from a CPU run of the SAME op set (deterministic
inputs), the analog of the reference's `check_consistency` GPU suite
(ref: tests/python/gpu/test_operator_gpu.py). Mosaic/XLA:TPU numeric
drift shows up here as per-op max-ulp / max-abs error.

Two modes (same file, different backends):
    env -u PYTHONPATH PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
        python benchmark/tpu_numerics.py --golden g.npz
    python benchmark/tpu_numerics.py --check g.npz   # on the device
(--golden stamps the producing platform into the npz and --check
refuses a non-cpu golden: the axon sitecustomize on PYTHONPATH can
override JAX_PLATFORMS=cpu, and a device-made golden would diff to 0.)

bench.py runs both automatically under BENCH_NUMERICS=1 (golden in a
CPU subprocess) and embeds the result in the bench JSON. The flash
attention kernels (fwd + bwd, NON-interpret) are additionally checked
in-process against the f32 jnp reference attention.
"""
import argparse
import json
import subprocess
import sys
import os

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _inputs(op, rs):
    """Deterministic representative inputs per op (identical across the
    golden and check processes)."""
    f = lambda *s: rs.rand(*s).astype("float32")  # noqa: E731
    return {
        # elemwise / transcendental
        "exp": [f(64, 64) * 4 - 2], "log": [f(64, 64) + 0.1],
        "tanh": [f(64, 64) * 6 - 3], "sigmoid": [f(64, 64) * 8 - 4],
        "erf": [f(64, 64) * 4 - 2], "rsqrt": [f(64, 64) + 0.05],
        # reductions
        "sum": [f(32, 128)], "mean": [f(32, 128)], "max": [f(32, 128)],
        "norm": [f(32, 128)],
        # linalg / matmul
        "dot": [f(96, 64), f(64, 80)],
        "linalg_gemm2": [f(32, 48), f(48, 24)],
        "linalg_potrf": [None],  # built below (SPD)
        "FullyConnected": [f(32, 64), f(16, 64), f(16)],
        # nn
        "Convolution": [f(4, 8, 16, 16), f(12, 8, 3, 3), f(12)],
        "BatchNorm": [f(8, 16, 8, 8), f(16), f(16), f(16), f(16)],
        "Pooling": [f(4, 8, 16, 16)],
        "softmax": [f(32, 100) * 10 - 5],
        "LayerNorm": [f(16, 128), f(128), f(128)],
        "log_softmax": [f(32, 100) * 10 - 5],
        # tensor manipulation
        "topk": [f(16, 200)], "sort": [f(16, 200)],
        "cumsum": [f(16, 128)],
        "take": [f(50, 8), rs.randint(0, 50, (20,)).astype("float32")],
    }[op]


def _call(op, ins):
    from mxnet_tpu.ops import registry
    import jax

    kwargs = {
        "sum": {"axis": 1}, "mean": {"axis": 1}, "max": {"axis": 1},
        "norm": {"ord": 2, "axis": 1},
        "FullyConnected": {"num_hidden": 16},
        "Convolution": {"kernel": (3, 3), "num_filter": 12,
                        "pad": (1, 1)},
        "BatchNorm": {"eps": 1e-3, "fix_gamma": False,
                      "_training": True},
        "Pooling": {"kernel": (2, 2), "stride": (2, 2),
                    "pool_type": "max"},
        "topk": {"k": 5, "ret_typ": "value"},
        "cumsum": {"axis": 1},
        "take": {"axis": 0},
    }.get(op, {})
    fn = registry.get_op(op).fn
    out = jax.jit(lambda *a: fn(*a, **kwargs))(*ins)
    if isinstance(out, (tuple, list)):
        out = out[0]
    return np.asarray(jax.block_until_ready(out))


OPS = ["exp", "log", "tanh", "sigmoid", "erf", "rsqrt",
       "sum", "mean", "max", "norm",
       "dot", "linalg_gemm2", "linalg_potrf", "FullyConnected",
       "Convolution", "BatchNorm", "Pooling", "softmax", "LayerNorm",
       "log_softmax",
       "topk", "sort", "cumsum", "take"]

# Per-op max-ULP budgets (VERDICT r4 item 3: "a sweep without a gate will
# silently absorb regressions"). Set at ~4x the worst value measured on
# the real chip in r4 (BENCH_r04.json per_op) so legitimate backend drift
# fits but an order-of-magnitude regression fails the sweep, bench, and
# CI. The matmul family at DEFAULT precision measures the documented
# bf16-multiply MXU policy (mxnet_tpu/precision.py), hence the loose
# 80k budgets there; the two precision-control entries prove the
# float32/highest escape hatches stay tight.
ULP_BUDGETS = {
    # log/tanh dropped 16384/8192 -> 256 in PR 9: ops/elemwise.py now
    # routes log through an exponent-split + log1p form (1 ULP vs f64
    # truth on CPU) and tanh through an expm1 form (4 ULP), so the
    # gate ENFORCES the campaign target instead of reporting the raw
    # TPU polynomial drift (was 3,396 / 1,267 measured in r05).
    "exp": 256, "log": 256, "tanh": 256, "sigmoid": 512, "erf": 64,
    "rsqrt": 32,
    "sum": 32, "mean": 32, "max": 8, "norm": 32,
    "dot": 80000, "linalg_gemm2": 80000, "linalg_potrf": 4096,
    "FullyConnected": 80000, "Convolution": 80000,
    # BatchNorm 50000 -> 64: batch_moments pins the mean to a
    # deterministic pairwise tree (bitwise equal across backends), so
    # the x-mean cancellation no longer amplifies reduction-order
    # noise; what remains is var last-bit noise through 1/sqrt
    "BatchNorm": 64, "Pooling": 8, "softmax": 512, "LayerNorm": 4096,
    "log_softmax": 4096,
    "topk": 8, "sort": 8, "cumsum": 64, "take": 8,
    "dot_precision_highest": 16,
    "dot_policy_float32": 16,
}
MODEL_REL_ERR_BUDGET = 0.02      # r4 measured 0.0045 (f32 conv decomp)
FLASH_FWD_REL_BUDGET = 1e-3      # r4 measured 1.07e-4
FLASH_BWD_ABS_BUDGET = 2e-2     # r4 measured 4.2e-3


def apply_gate(out):
    """Check the sweep result against the budgets; returns the list of
    breach strings and stamps out["gate"]."""
    breaches = []
    for op, rec in out["per_op"].items():
        budget = ULP_BUDGETS.get(op)
        if budget is not None and rec["max_ulp"] > budget:
            breaches.append("%s: %d ULP > budget %d"
                            % (op, rec["max_ulp"], budget))
    rel = out.get("model_resnet18_rel_err")
    if rel is not None and rel > MODEL_REL_ERR_BUDGET:
        breaches.append("model_resnet18_rel_err: %g > %g"
                        % (rel, MODEL_REL_ERR_BUDGET))
    if out["flash_fwd_rel_err"] > FLASH_FWD_REL_BUDGET:
        breaches.append("flash_fwd_rel_err: %g > %g"
                        % (out["flash_fwd_rel_err"], FLASH_FWD_REL_BUDGET))
    if out["flash_bwd_max_abs_err"] > FLASH_BWD_ABS_BUDGET:
        breaches.append("flash_bwd_max_abs_err: %g > %g"
                        % (out["flash_bwd_max_abs_err"],
                           FLASH_BWD_ABS_BUDGET))
    out["gate"] = {"ok": not breaches, "breaches": breaches}
    return breaches


def run_ops():
    results = {}
    import zlib
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.precision import matmul_precision
    from mxnet_tpu.ops import registry
    # The whole sweep is PINNED to the default policy: the budgets and the
    # module comments calibrate the DEFAULT bf16 MXU path, and an exported
    # MXTPU_MATMUL_PRECISION (applied globally at mxnet_tpu import) must
    # not silently shift what the per_op table measures. The two precision
    # controls below override locally, inside the pin.
    with matmul_precision("default"):
        rs = np.random.RandomState(42)
        a = rs.rand(96, 64).astype("float32")
        b = rs.rand(64, 80).astype("float32")
        # control: the matmul-family ULP gap is the TPU's default
        # bf16-multiply matmul policy, not a kernel bug — HIGHEST-precision
        # dot must collapse it by orders of magnitude
        hi = jax.jit(lambda x, y: jnp.dot(x, y, precision="highest"))
        results["dot_precision_highest"] = np.asarray(
            jax.block_until_ready(hi(a, b)))
        # second control THROUGH the repo's own op layer: the registry
        # `dot` under the float32 policy context (mxnet_tpu/precision.py)
        # must land within a few ULP of the CPU golden — proves the
        # user-facing knob, not just raw jnp, defeats the bf16 default
        with matmul_precision("float32"):
            out = jax.jit(registry.get_op("dot").fn)(a, b)
            results["dot_policy_float32"] = np.asarray(
                jax.block_until_ready(out))
        for op in OPS:
            # crc32, NOT hash(): str hashing is salted per process and the
            # golden/check runs live in different processes
            rs = np.random.RandomState(zlib.crc32(op.encode()) % (2 ** 31))
            if op == "linalg_potrf":
                a = rs.rand(24, 24).astype("float32")
                ins = [a @ a.T + 24 * np.eye(24, dtype="float32")]
            else:
                ins = _inputs(op, rs)
            results[op] = _call(op, ins)
    return results


def _max_ulp(a, b):
    """Max ULP distance between two same-shape f32 arrays (bit distance
    of the IEEE totally-ordered representation)."""
    ai = a.astype(np.float32).view(np.int32).astype(np.int64)
    bi = b.astype(np.float32).view(np.int32).astype(np.int64)
    # map negative floats onto the descending side of the number line
    ai = np.where(ai < 0, np.int64(-2147483648) - ai, ai)
    bi = np.where(bi < 0, np.int64(-2147483648) - bi, bi)
    return int(np.max(np.abs(ai - bi))) if a.size else 0


def check_flash():
    """Flash fwd+bwd (non-interpret when on TPU) vs jnp reference
    attention, both evaluated on THIS device in f32."""
    import importlib

    import jax
    import jax.numpy as jnp

    # the package __init__ re-exports the flash_attention FUNCTION under
    # the module's name; load the module itself
    FA = importlib.import_module(
        "mxnet_tpu.pallas_kernels.flash_attention")

    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.rand(2, 4, 256, 64).astype("float32") - 0.5)
    k = jnp.asarray(rs.rand(2, 4, 256, 64).astype("float32") - 0.5)
    v = jnp.asarray(rs.rand(2, 4, 256, 64).astype("float32") - 0.5)

    def loss_flash(q, k, v):
        return jnp.sum(FA.flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(FA.attention_reference(q, k, v, causal=True) ** 2)

    of, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    orf, gr = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    fwd_err = float(abs(np.asarray(of) - np.asarray(orf))
                    / max(abs(float(orf)), 1e-9))
    bwd_err = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(gf, gr))
    return {"flash_fwd_rel_err": round(fwd_err, 9),
            "flash_bwd_max_abs_err": round(bwd_err, 9),
            "pallas_active": bool(FA._use_pallas())}


def run_model():
    """Deterministic whole-model forward — the model-level analog of the
    op sweep (ref pattern: tests/python/gpu/test_operator_gpu.py runs
    full models on the device too). A thumbnail ResNet-18 eval forward
    exercises layout choices, conv/BN/pool fusion decisions, and the
    Gluon->jit tracing path that per-op checks cannot see."""
    import mxnet_tpu as mx
    from mxnet_tpu import random as mxrandom
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1

    import random as _pyrandom
    # deterministic init WITHOUT leaking reseeded global streams into
    # whatever runs after (bench.py calls this mid-process)
    py_state = _pyrandom.getstate()
    np_state = np.random.get_state()
    mx_state = (mxrandom._STATE.seed, mxrandom._STATE.counter,
                mxrandom._STATE.base_key, mxrandom._HOST_RNG.get_state())
    try:
        _pyrandom.seed(0)
        np.random.seed(0)
        mx.random.seed(0)
        from mxnet_tpu import autograd

        net = resnet18_v1(thumbnail=True)
        net.initialize()
        rs = np.random.RandomState(11)
        x = mx.nd.array(rs.rand(4, 3, 32, 32).astype("float32"))
        with autograd.pause():
            net(x)  # finish deferred init (host)
        # eager NDArrays are host-committed (default ctx cpu) and ops
        # follow operand placement — without explicit placement the
        # "device" check would silently run on the host CPU and match
        # the golden bit-exactly, checking nothing. reset_ctx /
        # as_in_context keep each array's .context consistent with the
        # buffer (Context('tpu') falls back to host on cpu-only runs,
        # preserving the golden process's behavior).
        tpu = mx.context.Context("tpu")
        net.collect_params().reset_ctx(tpu)
        x = x.as_in_context(tpu)
        with autograd.pause():
            out = net(x)
        return np.asarray(out.asnumpy())
    finally:
        _pyrandom.setstate(py_state)
        np.random.set_state(np_state)
        (mxrandom._STATE.seed, mxrandom._STATE.counter,
         mxrandom._STATE.base_key) = mx_state[:3]
        mxrandom._HOST_RNG.set_state(mx_state[3])


def sweep(golden_path):
    import jax
    golden = np.load(golden_path)
    # a golden accidentally produced on an accelerator (the axon
    # sitecustomize can override JAX_PLATFORMS=cpu) would make every
    # device-vs-golden diff read 0 — refuse it
    gplat = (str(golden["__platform__"]) if "__platform__" in golden
             else "<unstamped>")
    if gplat != "cpu":
        raise RuntimeError(
            "golden %s was produced on %r, not cpu — rerun --golden "
            "with the axon sitecustomize scrubbed from PYTHONPATH"
            % (golden_path, gplat))
    mine = run_ops()
    per_op = {}
    worst = None
    for op in OPS + ["dot_precision_highest", "dot_policy_float32"]:
        if op not in golden.files:  # golden from an older harness rev
            continue
        g = golden[op]
        m = mine[op]
        ulp = _max_ulp(m, g)
        per_op[op] = {"max_ulp": ulp,
                      "max_abs": float(np.max(np.abs(m - g)))
                      if g.size else 0.0}
        if worst is None or ulp > worst[1]:
            worst = (op, ulp)
    out = {
        "platform": jax.devices()[0].platform,
        "n_ops": len(OPS),
        "worst_op": worst[0],
        "worst_ulp": worst[1],
        "per_op": per_op,
    }
    if "__model__" in golden:
        m = run_model()
        g = golden["__model__"]
        # ULP distance is meaningless for near-zero logits (a sign flip
        # at 1e-8 is ~2^31 ULP), so the headline is max_abs relative to
        # the output scale; TPU f32 convs legitimately differ from CPU
        # (bf16-passes decomposition) and this is where that shows up
        max_abs = float(np.max(np.abs(m - g)))
        out["model_resnet18_max_abs"] = max_abs
        out["model_resnet18_rel_err"] = float(
            max_abs / (np.max(np.abs(g)) + 1e-12))
    out.update(check_flash())
    apply_gate(out)
    return out


def run_with_cpu_golden():
    """bench.py hook: golden in a CPU subprocess, check on this device."""
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        gpath = os.path.join(td, "golden.npz")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        # the axon accelerator plugin loads via a PYTHONPATH
        # sitecustomize and overrides JAX_PLATFORMS — the golden MUST
        # run on the real CPU backend, so scrub it down to the repo
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        try:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--golden",
                 gpath],
                env=env, check=True, capture_output=True, timeout=900)
        except subprocess.CalledProcessError as e:
            # surface the child's traceback — CalledProcessError's own
            # message drops the captured stderr
            tail = (e.stderr or b"").decode("utf-8", "replace")[-800:]
            raise RuntimeError(
                "golden subprocess failed (exit %d): %s"
                % (e.returncode, tail)) from e
        return sweep(gpath)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--golden", default=None)
    ap.add_argument("--check", default=None)
    args = ap.parse_args()
    if args.golden:
        import jax
        platform = jax.devices()[0].platform
        np.savez(args.golden, __platform__=np.array(platform),
                 __model__=run_model(),
                 **run_ops())
        print("wrote %s (%d ops, %s)" % (args.golden, len(OPS),
                                         platform))
        return
    out = sweep(args.check) if args.check else run_with_cpu_golden()
    print(json.dumps(out, indent=1))
    if not out["gate"]["ok"]:
        # the gate is the point of the sweep — a breach is a FAILURE,
        # not a statistic (VERDICT r4 weak #3)
        sys.exit("ULP gate breached: %s" % "; ".join(
            out["gate"]["breaches"]))


if __name__ == "__main__":
    main()
