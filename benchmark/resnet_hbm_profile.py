"""Attribute the ResNet-50 train step's HBM traffic per HLO instruction.

Round-3 established the step is HBM-bound (44 GB moved per b128 step,
XLA cost analysis) but never said WHERE the bytes go.  This script
compiles the exact bench.py step, parses the optimized HLO, and charges
each entry-computation instruction its operand+result bytes — the
static analog of a per-kernel HBM profile.  Output drives the round-4
fusion work (VERDICT r3 item 1).

Usage:  python benchmark/resnet_hbm_profile.py [--layout NHWC] [--batch 256]
"""
import argparse
import collections
import re
import sys

import numpy as np

DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(type_str):
    """Bytes of an HLO type string, incl. tuples: '(bf16[2,3]{...}, f32[4])'."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        if dt not in DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * DTYPE_BYTES[dt]
    return total


# '  %name = TYPE op(...)' — TYPE is everything up to the opcode token
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%?([\w\.\-]+)\s*=\s*(\(?.*?\)?)\s+([\w\-]+)\(")
_OPERAND_RE = re.compile(r"%([\w\.\-]+)")

SKIP_OPS = {"parameter", "constant", "get-tuple-element", "tuple", "bitcast",
            "after-all", "partition-id", "replica-id", "iota"}


def parse_entry(hlo_text):
    """Yield (name, opcode, result_bytes, operand_names, line) for ENTRY."""
    lines = hlo_text.splitlines()
    # find ENTRY computation block
    depth = 0
    in_entry = False
    sizes = {}
    instrs = []
    for ln in lines:
        if ln.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            m = _INSTR_RE.match(ln)
            if not m:
                continue
            name, type_str, opcode = m.groups()
            rb = shape_bytes(type_str)
            sizes[name] = rb
            # operands: everything inside the first (...) after opcode
            paren = ln[m.end() - 1:]
            # cut at '), ' metadata boundary — good enough for accounting
            ops = _OPERAND_RE.findall(paren)
            instrs.append((name, opcode, rb, ops, ln.strip()))
    return sizes, instrs


def categorize(opcode, line):
    if opcode == "fusion":
        m = re.search(r"kind=(\w+)", line)
        kind = m.group(1) if m else "?"
        for hint, cat in (("reduce", "fusion:reduce"),
                          ("conv", "fusion:conv"),
                          ("scatter", "fusion:scatter")):
            if hint in line:
                return "fusion:" + kind
        return "fusion:" + kind
    if opcode in ("convolution", "custom-call") and "conv" in line:
        return "convolution"
    return opcode


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layout", default="NCHW")
    ap.add_argument("--fused", action="store_true",
                    help="NHWC + save-only-conv-outs remat (BENCH_FUSED)")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--hlo-out", default=None,
                    help="also dump the optimized HLO text here")
    args = ap.parse_args()

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    import mxnet_tpu.optimizer as opt
    from mxnet_tpu.parallel import create_mesh, data_parallel, \
        ShardedTrainStep

    layout = "NHWC" if args.fused else args.layout
    net = resnet50_v1(layout=layout)
    net.initialize()
    net(mx.nd.array(np.zeros((1, 3, 224, 224), "float32")))
    if args.dtype != "float32":
        net.cast(args.dtype)
    mesh = create_mesh(devices=jax.devices()[:1], dp=1)
    step = ShardedTrainStep(net, SoftmaxCrossEntropyLoss(),
                            opt.create("sgd", learning_rate=0.01,
                                       momentum=0.9),
                            strategy=data_parallel(mesh),
                            remat_policy="conv_outs" if args.fused
                            else None)
    rng = np.random.RandomState(0)
    x = rng.rand(args.batch, 3, 224, 224).astype(args.dtype)
    y = rng.randint(0, 1000, (args.batch,)).astype("float32")
    xd, yd = step.place_batch(x, y)
    lowered = step.lower(xd, yd)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    print("== aggregate cost analysis ==")
    for k in ("bytes accessed", "flops", "optimal_seconds"):
        if k in ca:
            print("  %s: %.4g" % (k, ca[k]))
    hlo = compiled.as_text()
    if args.hlo_out:
        with open(args.hlo_out, "w") as f:
            f.write(hlo)
    sizes, instrs = parse_entry(hlo)

    rows = []
    for name, opcode, rb, ops, line in instrs:
        if opcode in SKIP_OPS:
            continue
        read = sum(sizes.get(o, 0) for o in ops if o in sizes)
        rows.append((rb + read, rb, read, name, opcode, line))
    rows.sort(reverse=True)

    total = sum(r[0] for r in rows)
    print("\n== static entry-computation traffic: %.2f GB ==" % (total / 1e9))

    by_cat = collections.Counter()
    cat_n = collections.Counter()
    for tot, rb, read, name, opcode, line in rows:
        cat = categorize(opcode, line)
        by_cat[cat] += tot
        cat_n[cat] += 1
    print("\n== by category ==")
    for cat, b in by_cat.most_common():
        print("  %-24s %8.2f GB  (%d instrs)" % (cat, b / 1e9, cat_n[cat]))

    print("\n== top %d instructions ==" % args.top)
    for tot, rb, read, name, opcode, line in rows[:args.top]:
        print("  %7.1f MB (w %6.1f r %7.1f)  %-12s %s"
              % (tot / 1e6, rb / 1e6, read / 1e6, opcode, line[:140]))

    # opcode histogram for transpose/copy hunting
    n_transpose = sum(1 for _, _, _, _, op, ln in rows
                      if op in ("transpose", "copy")
                      or (op == "fusion" and "transpose(" in ln))
    print("\ntranspose/copy-flavored entry instrs: %d" % n_transpose)


if __name__ == "__main__":
    main()
