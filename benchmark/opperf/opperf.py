#!/usr/bin/env python
"""Operator micro-benchmark harness (ref: benchmark/opperf/opperf.py).

Times forward and backward of registered ops on the attached device with
warmup + repeated runs, like the reference's profiler-driven op benchmark.
Usage:
    python benchmark/opperf/opperf.py                  # default op set
    python benchmark/opperf/opperf.py --ops add,dot    # subset
    python benchmark/opperf/opperf.py --json out.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402


def _rand(shape, dtype="float32", seed=0):
    import mxnet_tpu as mx
    rng = np.random.RandomState(seed)
    return mx.nd.array(rng.uniform(0.5, 1.5, shape).astype(dtype))


def default_specs():
    """Representative op set with benchmark shapes (mirrors the
    reference's per-category default inputs, opperf/rules/default_params.py)."""
    L = (1024, 1024)
    return {
        # unary elementwise
        "exp": lambda: ([_rand(L)], {}),
        "log": lambda: ([_rand(L)], {}),
        "sqrt": lambda: ([_rand(L)], {}),
        "tanh": lambda: ([_rand(L)], {}),
        "sigmoid": lambda: ([_rand(L)], {}),
        "relu": lambda: ([_rand(L)], {}),
        "erf": lambda: ([_rand(L)], {}),
        # binary / broadcast
        "add": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        "multiply": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        "broadcast_add": lambda: ([_rand(L), _rand((1024, 1), seed=1)], {}),
        "maximum": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        # reductions
        "sum": lambda: ([_rand(L)], {"axis": 1}),
        "mean": lambda: ([_rand(L)], {"axis": 1}),
        "max": lambda: ([_rand(L)], {"axis": 1}),
        "argmax": lambda: ([_rand(L)], {"axis": 1}),
        "softmax": lambda: ([_rand(L)], {}),
        "log_softmax": lambda: ([_rand(L)], {}),
        # linalg / MXU
        "dot": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        "batch_dot": lambda: ([_rand((32, 256, 256)),
                               _rand((32, 256, 256), seed=1)], {}),
        "FullyConnected": lambda: (
            [_rand((128, 1024)), _rand((1024, 1024), seed=1), None],
            {"num_hidden": 1024, "no_bias": True}),
        "Convolution": lambda: (
            [_rand((32, 64, 56, 56)), _rand((64, 64, 3, 3), seed=1), None],
            {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1),
             "no_bias": True}),
        # nn
        "BatchNorm": lambda: (
            [_rand((32, 64, 56, 56)), _rand((64,)), _rand((64,)),
             _rand((64,)), _rand((64,))], {}),
        "LayerNorm": lambda: (
            [_rand((128, 1024)), _rand((1024,)), _rand((1024,))], {}),
        "Pooling": lambda: (
            [_rand((32, 64, 56, 56))],
            {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
        # shape manipulation
        "transpose": lambda: ([_rand(L)], {}),
        "reshape": lambda: ([_rand(L)], {"shape": (512, 2048)}),
        "concat": lambda: ([_rand(L), _rand(L, seed=1)], {"dim": 1}),
        "tile": lambda: ([_rand((256, 256))], {"reps": (4, 4)}),
        # indexing
        "take": lambda: ([_rand(L),
                          _rand((1024,), "int32")], {}),
        "one_hot": lambda: ([_rand((4096,), "int32")], {"depth": 128}),
        # detection family (round 2; ref: contrib/deformable_convolution.cc,
        # psroi_pooling.cc, proposal.cc)
        "_contrib_DeformableConvolution": lambda: (
            [_rand((8, 64, 28, 28)), _rand((8, 18, 28, 28), seed=1),
             _rand((64, 64, 3, 3), seed=2)],
            {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1),
             "no_bias": True}),
        "_contrib_PSROIPooling": lambda: (
            [_rand((2, 4 * 49, 28, 28)),
             _rand_rois(16, 28)],
            {"spatial_scale": 1.0, "output_dim": 4, "pooled_size": 7,
             "group_size": 7}),
        # image family
        "_image_to_tensor": lambda: ([_rand((64, 224, 224, 3))], {}),
        "_image_resize": lambda: ([_rand((64, 224, 224, 3))],
                                  {"size": (112, 112)}),
        # quantized int8 (forward-only by nature)
        "_contrib_quantize_v2": lambda: ([_rand(L)], {}),
    }


def _rand_rois(n, size):
    import numpy as np
    rs = np.random.RandomState(7)
    x1 = rs.randint(0, size // 2, n)
    y1 = rs.randint(0, size // 2, n)
    rois = np.stack([np.zeros(n), x1, y1,
                     x1 + rs.randint(4, size // 2, n),
                     y1 + rs.randint(4, size // 2, n)], 1)
    import mxnet_tpu as mx
    return mx.nd.array(rois.astype("float32"))


def bench_op(name, make_inputs, warmup=3, runs=20, run_backward=True):
    """Time one op's forward (and backward through jax.vjp) in ms."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    args, kwargs = make_inputs()
    fn = getattr(mx.nd, name)

    def fwd():
        return fn(*args, **kwargs)

    for _ in range(max(warmup, 1)):  # >=1: the compile must not be timed
        out = fwd()
    jax.block_until_ready(out._data if hasattr(out, "_data")
                          else [o._data for o in out])
    t0 = time.perf_counter()
    for _ in range(runs):
        out = fwd()
    jax.block_until_ready(out._data if hasattr(out, "_data")
                          else [o._data for o in out])
    fwd_ms = (time.perf_counter() - t0) / runs * 1e3

    bwd_ms = None
    if run_backward:
        diffable = [a for a in args
                    if a is not None and np.issubdtype(a.dtype, np.floating)]
        if diffable:
            for a in diffable:
                a.attach_grad()

            def loss():
                with autograd.record():
                    out = fwd()
                    head = out[0] if isinstance(out, tuple) else out
                    s = head.sum()
                s.backward()
                return diffable[0].grad
            try:
                for _ in range(max(warmup, 1)):
                    g = loss()
                jax.block_until_ready(g._data)
                t0 = time.perf_counter()
                for _ in range(runs):
                    g = loss()
                jax.block_until_ready(g._data)
                bwd_ms = (time.perf_counter() - t0) / runs * 1e3
            except Exception as e:
                print("backward failed for %s: %s" % (name, str(e)[:80]),
                      file=sys.stderr)
    return {"op": name, "fwd_ms": round(fwd_ms, 4),
            "fwd_bwd_ms": round(bwd_ms, 4) if bwd_ms is not None else None}


def run_performance_test(ops=None, warmup=3, runs=20, run_backward=True):
    """ref: opperf.py run_op_benchmarks — returns a list of result dicts."""
    specs = default_specs()
    names = ops if ops else sorted(specs)
    results = []
    for name in names:
        if name not in specs:
            print("skipping %s (no benchmark spec)" % name, file=sys.stderr)
            continue
        try:
            results.append(bench_op(name, specs[name], warmup, runs,
                                    run_backward))
        except Exception as e:
            results.append({"op": name, "error": str(e)[:120]})
    return results


# ---------------------------------------------------------------------------
# Full-registry mode: auto-generated inputs for EVERY registered op
# (ref: opperf.py runs all registered ops with inputs synthesized from
# rules/default_params.py; here inputs come from the op fn signatures)
# ---------------------------------------------------------------------------

# tensor-input shape heuristics by parameter name (small shapes: the
# full sweep must finish in CI minutes). Profiles cover the common
# rank expectations; auto_spec tries them in order until the op runs.
_B, _D = 8, 32
_SHARED_SHAPES = {
    "weight": (_D, _D), "bias": (_D,),
    "gamma": (_D,), "beta": (_D,),
    "moving_mean": (_D,), "moving_var": (_D,),
    "label": (_B,),
    "indices": (_B,), "index": (_B,),
    "grid": (2, 2, 4, 4),
    "rois": (4, 5), "anchors": (1, 16, 4), "anchor": (1, 16, 4),
    "cls_pred": (2, 2, 16), "loc_pred": (2, 64),
    "cls_prob": (2, 2, 16), "bbox_pred": (2, 64),
    "im_info": (2, 3),
    "parameters": (4096,), "state": (1, _B, _D), "state_cell": (1, _B, _D),
    "A": (2, 8, 8), "B": (2, 8, 8), "C": (2, 8, 8),
    "pred": (10, 4, 8),                      # CTC: (seq, batch, alphabet)
    "sequence_length": (_B,), "lengths": (_B,), "len_arr": (_B,),
    "min_data": (1,), "max_data": (1,),
    "min_range": (1,), "max_range": (1,),
    "min_calib": (1,), "max_calib": (1,),
    "offset": (2, 18, 8, 8),                 # deformable conv offsets
    "mask": (2, 9, 8, 8),
}
_PROFILES = (
    # rank-2 activations (the default)
    {"data": (_B, _D), "x": (_B, _D), "a": (_B, _D), "b": (_B, _D),
     "lhs": (_B, _D), "rhs": (_B, _D), "data1": (_B, _D),
     "data2": (_B, _D), "shape_like": (_B, _D), "like": (_B, _D),
     "condition": (_B, _D), "mu": (_B, _D), "sigma": (_B, _D),
     "low": (_B, _D), "high": (_B, _D), "lam": (_B, _D),
     "alpha": (_B, _D), "loc": (_B, _D), "scale": (_B, _D)},
    # rank-4 NCHW (conv/pool/spatial families)
    {"data": (2, 4, 8, 8), "x": (2, 4, 8, 8), "a": (2, 4, 8, 8),
     "b": (2, 4, 8, 8), "lhs": (2, 4, 8, 8), "rhs": (2, 4, 8, 8),
     "data1": (2, 4, 8, 8), "data2": (2, 4, 8, 8),
     "shape_like": (2, 4, 8, 8), "like": (2, 4, 8, 8),
     "condition": (2, 4, 8, 8), "weight": (8, 4, 3, 3)},
    # rank-3 (sequence/batched-matmul families)
    {"data": (2, _B, _D), "x": (2, _B, _D), "a": (2, 8, 8),
     "b": (2, 8, 8), "lhs": (2, 8, 8), "rhs": (2, 8, 8),
     "data1": (2, _B, _D), "data2": (2, _B, _D),
     "shape_like": (2, _B, _D), "like": (2, _B, _D)},
    # square rank-2 (dot/linalg/contract families)
    {"data": (_D, _D), "x": (_D, _D), "a": (_D, _D), "b": (_D, _D),
     "lhs": (_D, _D), "rhs": (_D, _D), "data1": (_D, _D),
     "data2": (_D, _D)},
    # rank-3 HWC (host image ops)
    {"data": (16, 16, 3), "x": (16, 16, 3)},
)
_INT_TENSORS = {"indices", "index", "label"}


def _mk(name, shape, dtype="float32", lo=0.5, hi=1.5, seed=0):
    import mxnet_tpu as mx
    rng = np.random.RandomState(seed)
    if dtype.startswith("int"):
        return mx.nd.array(rng.randint(int(lo), int(hi), shape)
                           .astype(dtype))
    return mx.nd.array(rng.uniform(lo, hi, shape).astype(dtype))


# hand specs for ops whose input contracts the generic rules can't
# infer (shape coupling between inputs, packed encodings, special
# dtypes). Everything else is auto-generated.
_OP_OVERRIDES = {
    # layout NTC: pred (batch, seq, alphabet); label (batch, max_len)
    "CTCLoss": lambda: ([_mk("p", (4, 10, 8)),
                         _mk("l", (4, 2), "int32", 1, 7)], {}),
    "MultiBoxTarget": lambda: ([_mk("a", (1, 16, 4), lo=0.0, hi=1.0),
                                _mk("l", (2, 2, 5), lo=0.1, hi=0.5),
                                _mk("c", (2, 2, 16))], {}),
    # default scales x ratios = 12 anchors: cls 2*12 ch, bbox 4*12 ch
    "Proposal": lambda: ([_mk("c", (1, 24, 8, 8)),
                          _mk("b", (1, 48, 8, 8), lo=-0.1, hi=0.1),
                          _mk("i", (1, 3), lo=8, hi=9)], {}),
    "MultiProposal": lambda: ([_mk("c", (1, 24, 8, 8)),
                               _mk("b", (1, 48, 8, 8), lo=-0.1, hi=0.1),
                               _mk("i", (1, 3), lo=8, hi=9)], {}),
    "GridGenerator": lambda: ([_mk("d", (2, 6))],
                              {"transform_type": "affine",
                               "target_shape": (4, 4)}),
    "SpatialTransformer": lambda: ([_mk("d", (2, 4, 8, 8)),
                                    _mk("l", (2, 6))],
                                   {"transform_type": "affine",
                                    "target_shape": (4, 4)}),
    "DeformableConvolution": lambda: (
        [_mk("d", (2, 4, 8, 8)), _mk("o", (2, 18, 8, 8), lo=-1, hi=1),
         _mk("w", (8, 4, 3, 3))],
        {"kernel": (3, 3), "num_filter": 8, "pad": (1, 1),
         "no_bias": True}),
    "Deconvolution": lambda: ([_mk("d", (2, 4, 8, 8)),
                               _mk("w", (4, 8, 3, 3))],
                              {"kernel": (3, 3), "num_filter": 8,
                               "no_bias": True}),
    "Pad": lambda: ([_mk("d", (2, 4, 8, 8))],
                    {"mode": "constant",
                     "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    "Reshape": lambda: ([_mk("d", (_B, _D))], {"shape": (_D, _B)}),
    "broadcast_to": lambda: ([_mk("d", (1, _D))], {"shape": (_B, _D)}),
    "cast_storage": lambda: ([_mk("d", (_B, _D))], {"stype": "default"}),
    "RNN": lambda: ([_mk("d", (5, 2, 8)), _mk("p", (4096,), lo=-0.1,
                                              hi=0.1),
                     _mk("s", (1, 2, 8))],
                    {"state_size": 8, "num_layers": 1,
                     "mode": "rnn_tanh"}),
    "gather_nd": lambda: ([_mk("d", (_B, _D)),
                           _mk("i", (2, 4), "int32", 0, 7)], {}),
    "scatter_nd": lambda: ([_mk("d", (4,)),
                            _mk("i", (1, 4), "int32", 0, 7)],
                           {"shape": (_B,)}),
    "_scatter_set_nd": lambda: ([_mk("d", (_B,)), _mk("v", (4,)),
                                 _mk("i", (1, 4), "int32", 0, 7)],
                                {"shape": (_B,)}),
    "choose_element_0index": lambda: ([_mk("d", (_B, _D)),
                                       _mk("i", (_B,), "int32", 0,
                                           _D - 1)], {}),
    "fill_element_0index": lambda: ([_mk("d", (_B, _D)),
                                     _mk("v", (_B,)),
                                     _mk("i", (_B,), "int32", 0,
                                         _D - 1)], {}),
    "_unravel_index": lambda: ([_mk("i", (_B,), "int32", 0, 63)],
                               {"shape": (8, 8)}),
    "_linalg_maketrian": lambda: ([_mk("d", (2, 36))], {}),
    "_contrib_quantized_conv": lambda: (
        [_mk("d", (2, 4, 8, 8), "int8", -127, 127),
         _mk("w", (8, 4, 3, 3), "int8", -127, 127),
         _mk("bz", (8,), "int8", -127, 127),
         _mk("mn", (1,), lo=-1, hi=-0.9), _mk("mx", (1,), lo=0.9, hi=1),
         _mk("wmn", (1,), lo=-1, hi=-0.9),
         _mk("wmx", (1,), lo=0.9, hi=1),
         _mk("bmn", (1,), lo=-1, hi=-0.9),
         _mk("bmx", (1,), lo=0.9, hi=1)],
        {"kernel": (3, 3), "num_filter": 8, "no_bias": True}),
    "_contrib_quantized_concat": lambda: (
        [_mk("a", (_B, _D), "int8", -127, 127),
         _mk("b", (_B, _D), "int8", -127, 127),
         _mk("amn", (1,), lo=-1, hi=-0.9), _mk("amx", (1,), lo=0.9, hi=1),
         _mk("bmn", (1,), lo=-1, hi=-0.9),
         _mk("bmx", (1,), lo=0.9, hi=1)],
        {"num_args": 2, "dim": 1}),
    "_contrib_calibrate_entropy": lambda: (
        [_mk("h", (64,), lo=0, hi=100),
         _mk("e", (65,), lo=-1, hi=1)], {"num_quantized_bins": 16}),
    "bernoulli": lambda: ([_mk("p", (_B, _D), lo=0.1, hi=0.9)], {}),
    # internal CSR kernel seam (ndarray/sparse.py): CSR structure rides
    # as static kwargs, so synthesize a consistent 8x32 sparse matrix
    "_sparse_dot_csr_dense": lambda: (
        [_mk("v", (64,)), _mk("d", (_D, 16))],
        {"col_indices": np.tile(np.arange(8) * 4, 8).astype(np.int64),
         "indptr": (np.arange(9) * 8).astype(np.int64),
         "num_rows": 8}),
    "negative": lambda: ([_mk("x", (_B, _D))], {}),
    "_contrib_hawkesll": lambda: (
        [_mk("mu", (2, 3), lo=0.1, hi=0.5),
         _mk("al", (3,), lo=0.1, hi=0.4),
         _mk("be", (3,), lo=0.5, hi=1.0),
         _mk("st", (2, 3), lo=0.5, hi=1.0),
         _mk("lags", (2, 5), lo=0.01, hi=0.2),
         _mk("marks", (2, 5), "int32", 0, 2),
         _mk("vl", (2,), "int32", 4, 5),
         _mk("maxt", (2,), lo=2.0, hi=3.0)], {}),
}


def _upd(n_tensors, **hyper):
    """Fused-optimizer update-op spec: n same-shape tensors (weight +
    grad + states) plus runtime hyperparameters. lr etc. default to
    None in the registry fns but are REQUIRED by the generated nd
    wrappers (the reference marks them required attrs), so auto_spec's
    optional-param skip can't synthesize them."""
    def make():
        return ([_mk("t%d" % i, (_B, _D), seed=i)
                 for i in range(n_tensors)], dict(hyper))
    return make


def _multi_upd(n_per, groups=2, preloaded=False):
    """multi_* update ops: `groups` interleaved (weight, grad, states)
    tuples; preloaded variants carry the lr/wd vectors as the two
    trailing DATA tensors instead of attrs."""
    def make():
        args = [_mk("m%d" % i, (_B, _D), seed=i)
                for i in range(n_per * groups)]
        if preloaded:
            args += [_mk("lrs", (groups,), lo=0.01, hi=0.1),
                     _mk("wds", (groups,), lo=0.0, hi=0.01)]
            return args, {"num_weights": groups}
        return args, {"num_weights": groups,
                      "lrs": [0.05] * groups, "wds": [0.0] * groups}
    return make


_OP_OVERRIDES.update({
    "sgd_update": _upd(2, lr=0.05),
    "sgd_mom_update": _upd(3, lr=0.05),
    "mp_sgd_update": _upd(3, lr=0.05),
    "mp_sgd_mom_update": _upd(4, lr=0.05),
    "signsgd_update": _upd(2, lr=0.05),
    "signum_update": _upd(3, lr=0.05),
    "nag_mom_update": _upd(3, lr=0.05),
    "mp_nag_mom_update": _upd(4, lr=0.05),
    "adam_update": _upd(4, lr=0.05),
    "ftml_update": _upd(5, lr=0.05, t=1),
    "ftrl_update": _upd(4, lr=0.05),
    "rmsprop_update": _upd(3, lr=0.05),
    "rmspropalex_update": _upd(5, lr=0.05),
    "adamw_update": _upd(4, rescale_grad=1.0, lr=0.05, eta=1.0),
    "mp_adamw_update": _upd(5, rescale_grad=1.0, lr=0.05, eta=1.0),
    "lamb_update_phase1": _upd(4, lr=0.05),
    # phase2's r1/r2 are the per-tensor scalar norms, shape (1,) — a
    # full-tensor ratio would time a different computation
    "lamb_update_phase2": lambda: (
        [_mk("w", (_B, _D)), _mk("g", (_B, _D), seed=1),
         _mk("r1", (1,), lo=1.0, hi=2.0), _mk("r2", (1,), lo=1.0, hi=2.0)],
        {"lr": 0.05}),
    "group_adagrad_update": _upd(3, lr=0.05),
    "multi_sgd_update": _multi_upd(2),
    "multi_sgd_mom_update": _multi_upd(3),
    "multi_mp_sgd_update": _multi_upd(3),
    "multi_mp_sgd_mom_update": _multi_upd(4),
    "preloaded_multi_sgd_update": _multi_upd(2, preloaded=True),
    "preloaded_multi_sgd_mom_update": _multi_upd(3, preloaded=True),
    "preloaded_multi_mp_sgd_update": _multi_upd(3, preloaded=True),
    "preloaded_multi_mp_sgd_mom_update": _multi_upd(4, preloaded=True),
    # creation ops whose nd wrapper exposes required positionals
    # (val / stop) under different names than the registry fn
    "full": lambda: ([(_B, _D), 2.0], {}),
    "arange": lambda: ([0.0, float(_B * _D)], {}),
})

# values for REQUIRED static params, by name (optional params keep their
# defaults)
_STATIC_DEFAULTS = {
    "kernel": (3, 3), "num_filter": 8, "num_hidden": _D,
    "shape": (_B * _D,), "axis": 0, "axes": None, "dim": 0,
    "depth": 16, "reps": (2, 2), "size": 2, "k": 1, "begin": 0, "end": 4,
    "scalar": 2.0, "p": 0.5, "num_outputs": 2, "num_args": 2,
    "pooled_size": 2, "output_dim": 4, "spatial_scale": 1.0,
    "group_size": 2, "rhs_begin": 0, "rhs_end": 1, "lhs_begin": 0,
    "lhs_end": 1, "num_group": 1, "eps": 1e-5, "dtype": "float32",
    "src_dtype": "float32", "target_dtype": "float32",
    "sample_ratio": 1, "state_size": _D, "num_layers": 1, "mode": "rnn_tanh",
    "act_type": "relu", "transform_type": "affine", "target_shape": (4, 4),
    "min_calib_range": -1.0, "max_calib_range": 1.0, "nms_threshold": 0.5,
    "overlap_threshold": 0.5, "n": 2, "num_sampled": 4, "range_max": 16,
    "slice_mode": "center",
}


def _make_tensor(name, seed, profile):
    import mxnet_tpu as mx
    rng = np.random.RandomState(seed)
    shape = profile.get(name) or _SHARED_SHAPES.get(name) or (_B, _D)
    if name in _INT_TENSORS:
        return mx.nd.array(rng.randint(0, 4, shape).astype("int32"))
    return mx.nd.array(rng.uniform(0.5, 1.5, shape).astype("float32"))


def auto_spec(opdef, profile):
    """Synthesize (args, kwargs) for an op from its fn signature using
    one shape profile, or raise ValueError naming what could not be
    synthesized. Rule: every leading required parameter that is not a
    known static is a tensor input (the registry convention the symbol
    wrappers also rely on)."""
    import inspect
    sig = inspect.signature(opdef.fn)
    args = []
    kwargs = {}
    in_input_prefix = True
    seed = 0
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            # variadic ops get two tensors
            args.extend([_make_tensor("data", 0, profile),
                         _make_tensor("data", 1, profile)])
            in_input_prefix = False
            continue
        if p.kind == inspect.Parameter.VAR_KEYWORD:
            continue
        if p.name in ("key", "_training", "out", "name"):
            continue
        required = p.default is inspect.Parameter.empty
        if in_input_prefix and required and \
                p.name not in _STATIC_DEFAULTS:
            args.append(_make_tensor(p.name, seed, profile))
            seed += 1
            continue
        in_input_prefix = False
        if not required:
            continue  # optional static: keep the default
        if p.name in _STATIC_DEFAULTS:
            v = _STATIC_DEFAULTS[p.name]
            if v is not None:
                kwargs[p.name] = v
            continue
        raise ValueError("no synthesis rule for required param %r"
                         % p.name)
    if not args and "shape" not in kwargs:
        # creation ops (zeros/arange/samplers) run tensor-free if they
        # accept a shape
        if "shape" in sig.parameters:
            kwargs["shape"] = (_B, _D)
        else:
            raise ValueError("op takes no tensor inputs")
    return args, kwargs


def _bench_callable(fn, runs, warmup):
    """Per-call synchronous timing: every iteration blocks until ready,
    so no async pipelining can hide (or fabricate) dispatch cost. This
    is a HOST-side microbench harness — on a remote-tunnel TPU attach,
    per-call sync includes tunnel RTT and inflates small ops; run the
    full sweep on CPU (CI) or a locally attached device."""
    import jax

    def _ready(out):
        leaves = out if isinstance(out, (tuple, list)) else [out]
        jax.block_until_ready([getattr(o, "_data", o) for o in leaves
                               if o is not None])

    for _ in range(max(warmup, 1)):
        _ready(fn())
    t0 = time.perf_counter()
    for _ in range(runs):
        _ready(fn())
    return (time.perf_counter() - t0) / runs * 1e3


def bench_registry_op(name, opdef, runs=5, warmup=1):
    """Benchmark one registry op with auto inputs: the mx.nd dispatch
    path AND the jnp-native baseline (calling the registered pure fn on
    raw jax arrays — the lower bound the dispatch layer adds overhead
    to). Input shapes come from the first profile the op accepts."""
    import inspect
    import jax
    import mxnet_tpu as mx

    fn = getattr(mx.nd, name, None)
    if fn is None:
        # ops registered after namespace population (internal seams
        # like _sparse_dot_csr_dense) still dispatch via the registry;
        # bind the opdef once so the timed loop pays the same dispatch
        # cost as mx.nd-exposed ops (no per-call name lookup)
        from mxnet_tpu.ndarray.register import invoke as _invoke
        fn = lambda *a, **kw: _invoke(opdef, a, kw)  # noqa: E731
    args = kwargs = None
    last_err = None
    if name in _OP_OVERRIDES:
        args, kwargs = _OP_OVERRIDES[name]()
    else:
        for profile in _PROFILES:
            try:
                cand_args, cand_kwargs = auto_spec(opdef, profile)
                fn(*cand_args, **cand_kwargs)  # dry run, this profile
                args, kwargs = cand_args, cand_kwargs
                break
            except Exception as e:  # noqa: BLE001 — next rank profile
                last_err = e
        if args is None:
            # creation ops whose params all default (arange/eye/window
            # fns/samplers): run argument-free
            try:
                fn()
                args, kwargs = [], {}
            except Exception:  # noqa: BLE001
                raise last_err
    nd_ms = _bench_callable(lambda: fn(*args, **kwargs), runs, warmup)

    # jnp-native baseline: the raw registered function
    raw = [getattr(a, "_data", a) for a in args]
    sig = inspect.signature(opdef.fn)
    extra = {}
    if "key" in sig.parameters:
        extra["key"] = jax.random.PRNGKey(0)
    if "_training" in sig.parameters:
        extra["_training"] = False
    base_ms = _bench_callable(
        lambda: opdef.fn(*raw, **kwargs, **extra), runs, warmup)
    return {"op": name, "fwd_ms": round(nd_ms, 4),
            "jnp_native_ms": round(base_ms, 4),
            "dispatch_overhead_ms": round(nd_ms - base_ms, 4)}


# pseudo-ops that are not benchmarkable operators: fused subgraph
# regions are graph-local artifacts (symbol/subgraph.py registers one
# per partition call), and Custom is the Python-callback bridge whose
# inputs are defined by the user callback, not a signature
_SKIP_PREFIXES = ("_subgraph_",)
_SKIP_OPS = {"Custom"}


def run_full_registry(runs=5, warmup=1, verbose=False, ops=None):
    """One command over EVERY registered op name (aliases share their
    canonical OpDef's measurement; `ops` filters to a subset by any
    registered name). Forward-path timing only. Returns the summary
    dict that --full emits as JSON."""
    from mxnet_tpu.ops import registry as _registry

    names = [n for n in _registry.list_ops()
             if n not in _SKIP_OPS
             and not n.startswith(_SKIP_PREFIXES)]
    skipped = len(_registry.list_ops()) - len(names)
    canonical = {}
    for n in names:
        opdef = _registry.get_op(n)
        # canonical = any registered name with a hand spec, else the
        # first seen — so _OP_OVERRIDES keys match regardless of how
        # alias names sort
        if n in _OP_OVERRIDES or id(opdef) not in canonical:
            canonical[id(opdef)] = n

    if ops:
        filtered = [n for n in ops
                    if n in _SKIP_OPS or n.startswith(_SKIP_PREFIXES)]
        if filtered:
            raise ValueError(
                "requested pseudo-ops are not benchmarkable: %s"
                % filtered)
        wanted = {id(_registry.get_op(n)) for n in ops}
        canonical = {k: v for k, v in canonical.items() if k in wanted}

    results, errors = {}, {}
    for _oid, cname in sorted(canonical.items(), key=lambda kv: kv[1]):
        opdef = _registry.get_op(cname)
        try:
            results[cname] = bench_registry_op(cname, opdef, runs, warmup)
        except Exception as e:  # noqa: BLE001 — record, keep sweeping
            errors[cname] = "%s: %s" % (type(e).__name__, str(e)[:100])
        if verbose:
            status = "ok" if cname in results else "ERR"
            print("%-40s %s" % (cname, status), file=sys.stderr)

    ok = sorted(results.values(), key=lambda r: -r["fwd_ms"])
    return {
        "registry_names": len(names),
        "skipped_pseudo_ops": skipped,
        "unique_ops": len(canonical),
        "measured": len(results),
        "errors": len(errors),
        "coverage_pct": round(100.0 * len(results)
                              / max(len(canonical), 1), 1),
        "top10_slowest": ok[:10],
        "results": results,
        "error_detail": errors,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="op micro-benchmarks (ref: benchmark/opperf)")
    parser.add_argument("--ops", default=None,
                        help="comma-separated op subset")
    parser.add_argument("--runs", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--no-backward", action="store_true")
    parser.add_argument("--full", action="store_true",
                        help="sweep EVERY registered op with "
                             "auto-generated inputs (small shapes)")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--json", default=None, help="write results here")
    args = parser.parse_args(argv)
    if args.full:
        ops = args.ops.split(",") if args.ops else None
        summary = run_full_registry(runs=max(1, args.runs // 4),
                                    warmup=args.warmup,
                                    verbose=args.verbose, ops=ops)
        print("registry names: %d (unique ops %d), measured %d, "
              "errors %d -> %.1f%% coverage (forward-path timing)"
              % (summary["registry_names"], summary["unique_ops"],
                 summary["measured"], summary["errors"],
                 summary["coverage_pct"]))
        print("%-36s %10s %14s" % ("10 slowest", "fwd (ms)",
                                   "jnp-native (ms)"))
        for r in summary["top10_slowest"]:
            print("%-36s %10.4f %14.4f" % (r["op"], r["fwd_ms"],
                                           r["jnp_native_ms"]))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(summary, f, indent=2)
        return 0
    ops = args.ops.split(",") if args.ops else None
    results = run_performance_test(ops, args.warmup, args.runs,
                                   not args.no_backward)
    print("%-18s %12s %12s" % ("op", "fwd (ms)", "fwd+bwd (ms)"))
    for r in results:
        if "error" in r:
            print("%-18s ERROR: %s" % (r["op"], r["error"]))
        else:
            print("%-18s %12.4f %12s" % (
                r["op"], r["fwd_ms"],
                "%.4f" % r["fwd_bwd_ms"] if r["fwd_bwd_ms"] else "-"))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
