#!/usr/bin/env python
"""Operator micro-benchmark harness (ref: benchmark/opperf/opperf.py).

Times forward and backward of registered ops on the attached device with
warmup + repeated runs, like the reference's profiler-driven op benchmark.
Usage:
    python benchmark/opperf/opperf.py                  # default op set
    python benchmark/opperf/opperf.py --ops add,dot    # subset
    python benchmark/opperf/opperf.py --json out.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402


def _rand(shape, dtype="float32", seed=0):
    import mxnet_tpu as mx
    rng = np.random.RandomState(seed)
    return mx.nd.array(rng.uniform(0.5, 1.5, shape).astype(dtype))


def default_specs():
    """Representative op set with benchmark shapes (mirrors the
    reference's per-category default inputs, opperf/rules/default_params.py)."""
    L = (1024, 1024)
    return {
        # unary elementwise
        "exp": lambda: ([_rand(L)], {}),
        "log": lambda: ([_rand(L)], {}),
        "sqrt": lambda: ([_rand(L)], {}),
        "tanh": lambda: ([_rand(L)], {}),
        "sigmoid": lambda: ([_rand(L)], {}),
        "relu": lambda: ([_rand(L)], {}),
        "erf": lambda: ([_rand(L)], {}),
        # binary / broadcast
        "add": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        "multiply": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        "broadcast_add": lambda: ([_rand(L), _rand((1024, 1), seed=1)], {}),
        "maximum": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        # reductions
        "sum": lambda: ([_rand(L)], {"axis": 1}),
        "mean": lambda: ([_rand(L)], {"axis": 1}),
        "max": lambda: ([_rand(L)], {"axis": 1}),
        "argmax": lambda: ([_rand(L)], {"axis": 1}),
        "softmax": lambda: ([_rand(L)], {}),
        "log_softmax": lambda: ([_rand(L)], {}),
        # linalg / MXU
        "dot": lambda: ([_rand(L), _rand(L, seed=1)], {}),
        "batch_dot": lambda: ([_rand((32, 256, 256)),
                               _rand((32, 256, 256), seed=1)], {}),
        "FullyConnected": lambda: (
            [_rand((128, 1024)), _rand((1024, 1024), seed=1), None],
            {"num_hidden": 1024, "no_bias": True}),
        "Convolution": lambda: (
            [_rand((32, 64, 56, 56)), _rand((64, 64, 3, 3), seed=1), None],
            {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1),
             "no_bias": True}),
        # nn
        "BatchNorm": lambda: (
            [_rand((32, 64, 56, 56)), _rand((64,)), _rand((64,)),
             _rand((64,)), _rand((64,))], {}),
        "LayerNorm": lambda: (
            [_rand((128, 1024)), _rand((1024,)), _rand((1024,))], {}),
        "Pooling": lambda: (
            [_rand((32, 64, 56, 56))],
            {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
        # shape manipulation
        "transpose": lambda: ([_rand(L)], {}),
        "reshape": lambda: ([_rand(L)], {"shape": (512, 2048)}),
        "concat": lambda: ([_rand(L), _rand(L, seed=1)], {"dim": 1}),
        "tile": lambda: ([_rand((256, 256))], {"reps": (4, 4)}),
        # indexing
        "take": lambda: ([_rand(L),
                          _rand((1024,), "int32")], {}),
        "one_hot": lambda: ([_rand((4096,), "int32")], {"depth": 128}),
        # detection family (round 2; ref: contrib/deformable_convolution.cc,
        # psroi_pooling.cc, proposal.cc)
        "_contrib_DeformableConvolution": lambda: (
            [_rand((8, 64, 28, 28)), _rand((8, 18, 28, 28), seed=1),
             _rand((64, 64, 3, 3), seed=2)],
            {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1),
             "no_bias": True}),
        "_contrib_PSROIPooling": lambda: (
            [_rand((2, 4 * 49, 28, 28)),
             _rand_rois(16, 28)],
            {"spatial_scale": 1.0, "output_dim": 4, "pooled_size": 7,
             "group_size": 7}),
        # image family
        "_image_to_tensor": lambda: ([_rand((64, 224, 224, 3))], {}),
        "_image_resize": lambda: ([_rand((64, 224, 224, 3))],
                                  {"size": (112, 112)}),
        # quantized int8 (forward-only by nature)
        "_contrib_quantize_v2": lambda: ([_rand(L)], {}),
    }


def _rand_rois(n, size):
    import numpy as np
    rs = np.random.RandomState(7)
    x1 = rs.randint(0, size // 2, n)
    y1 = rs.randint(0, size // 2, n)
    rois = np.stack([np.zeros(n), x1, y1,
                     x1 + rs.randint(4, size // 2, n),
                     y1 + rs.randint(4, size // 2, n)], 1)
    import mxnet_tpu as mx
    return mx.nd.array(rois.astype("float32"))


def bench_op(name, make_inputs, warmup=3, runs=20, run_backward=True):
    """Time one op's forward (and backward through jax.vjp) in ms."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    args, kwargs = make_inputs()
    fn = getattr(mx.nd, name)

    def fwd():
        return fn(*args, **kwargs)

    for _ in range(max(warmup, 1)):  # >=1: the compile must not be timed
        out = fwd()
    jax.block_until_ready(out._data if hasattr(out, "_data")
                          else [o._data for o in out])
    t0 = time.perf_counter()
    for _ in range(runs):
        out = fwd()
    jax.block_until_ready(out._data if hasattr(out, "_data")
                          else [o._data for o in out])
    fwd_ms = (time.perf_counter() - t0) / runs * 1e3

    bwd_ms = None
    if run_backward:
        diffable = [a for a in args
                    if a is not None and np.issubdtype(a.dtype, np.floating)]
        if diffable:
            for a in diffable:
                a.attach_grad()

            def loss():
                with autograd.record():
                    out = fwd()
                    head = out[0] if isinstance(out, tuple) else out
                    s = head.sum()
                s.backward()
                return diffable[0].grad
            try:
                for _ in range(max(warmup, 1)):
                    g = loss()
                jax.block_until_ready(g._data)
                t0 = time.perf_counter()
                for _ in range(runs):
                    g = loss()
                jax.block_until_ready(g._data)
                bwd_ms = (time.perf_counter() - t0) / runs * 1e3
            except Exception as e:
                print("backward failed for %s: %s" % (name, str(e)[:80]),
                      file=sys.stderr)
    return {"op": name, "fwd_ms": round(fwd_ms, 4),
            "fwd_bwd_ms": round(bwd_ms, 4) if bwd_ms is not None else None}


def run_performance_test(ops=None, warmup=3, runs=20, run_backward=True):
    """ref: opperf.py run_op_benchmarks — returns a list of result dicts."""
    specs = default_specs()
    names = ops if ops else sorted(specs)
    results = []
    for name in names:
        if name not in specs:
            print("skipping %s (no benchmark spec)" % name, file=sys.stderr)
            continue
        try:
            results.append(bench_op(name, specs[name], warmup, runs,
                                    run_backward))
        except Exception as e:
            results.append({"op": name, "error": str(e)[:120]})
    return results


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="op micro-benchmarks (ref: benchmark/opperf)")
    parser.add_argument("--ops", default=None,
                        help="comma-separated op subset")
    parser.add_argument("--runs", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--no-backward", action="store_true")
    parser.add_argument("--json", default=None, help="write results here")
    args = parser.parse_args(argv)
    ops = args.ops.split(",") if args.ops else None
    results = run_performance_test(ops, args.warmup, args.runs,
                                   not args.no_backward)
    print("%-18s %12s %12s" % ("op", "fwd (ms)", "fwd+bwd (ms)"))
    for r in results:
        if "error" in r:
            print("%-18s ERROR: %s" % (r["op"], r["error"]))
        else:
            print("%-18s %12.4f %12s" % (
                r["op"], r["fwd_ms"],
                "%.4f" % r["fwd_bwd_ms"] if r["fwd_bwd_ms"] else "-"))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
