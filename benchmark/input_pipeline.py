"""Standalone input-pipeline benchmark (VERDICT r2 item 5).

Synthetic JPEG .rec -> ImageRecordIter (uint8 feed, threaded decode,
prefetch) -> sustained img/s, plus host->device bandwidth. One command:

    python benchmark/input_pipeline.py

Prints one JSON line. The same measurement runs inside bench.py's
resnet entry (key "input_pipeline") so BENCH_r* records it next to the
compute-only number.

ref slot: the reference benchmarks its pipeline via
tools/bandwidth + the OMP decode path of iter_image_recordio_2.cc;
here decode is cv2 (GIL-releasing) with batch-level vectorized
normalize — see mxnet_tpu/io/image_iter.py for the design rules.
"""
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench import bench_input_pipeline  # noqa: E402


if __name__ == "__main__":
    print(json.dumps(bench_input_pipeline()))
