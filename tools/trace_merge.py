#!/usr/bin/env python
"""Merge per-rank chrome-trace shards into one job-wide trace.

Each rank of a distributed run dumps its own trace shard
(``profiler.dump()``) with ``pid=rank`` and a ``metadata`` block
carrying the clock offsets measured on the kvstore heartbeat path.
This CLI (a thin wrapper over ``profiler.merge_traces``) aligns every
shard onto PS server 0's clock and writes one chrome://tracing /
Perfetto file in which the wire flow events (``ph:"s"/"f"``) draw
client→server causality arrows per push/pull/barrier.

    python tools/trace_merge.py trace_rank0.json trace_rank1.json \
        -o merged.json

``--no-align`` keeps raw per-rank timestamps (debugging the alignment
itself). Exit status is non-zero when no flow pairs match while both
sides emitted flows — the signature of mismatched shards.
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-rank chrome-trace shards into one trace")
    ap.add_argument("shards", nargs="+",
                    help="per-rank trace JSON files (profiler.dump())")
    ap.add_argument("-o", "--output", default="merged_trace.json",
                    help="merged trace path (default: %(default)s)")
    ap.add_argument("--no-align", action="store_true",
                    help="skip heartbeat-based clock alignment")
    args = ap.parse_args(argv)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu import profiler

    _, summary = profiler.merge_traces(
        args.shards, output=args.output, align=not args.no_align)
    print("merged %d shard(s) (ranks %s) -> %s: %d events"
          % (len(args.shards), summary["ranks"], args.output,
             summary["events"]))
    for rank, off in sorted(summary["offsets_us"].items()):
        print("  rank %s: clock offset %+.1f us" % (rank, off))
    print("  flow events: %d started, %d finished, %d paired"
          % (summary["flows_started"], summary["flows_finished"],
             summary["flows_paired"]))
    if summary["flows_started"] and summary["flows_finished"] \
            and not summary["flows_paired"]:
        print("error: no client/server flow pair matched — are these "
              "shards from the same run?", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
