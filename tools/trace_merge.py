#!/usr/bin/env python
"""Merge per-rank chrome-trace shards into one job-wide trace.

Each rank of a distributed run dumps its own trace shard
(``profiler.dump()``) with ``pid=rank`` and a ``metadata`` block
carrying the clock offsets measured on the kvstore heartbeat path.
This CLI (a thin wrapper over ``profiler.merge_traces``) aligns every
shard onto PS server 0's clock and writes one chrome://tracing /
Perfetto file in which the wire flow events (``ph:"s"/"f"``) draw
client→server causality arrows per push/pull/barrier.

Flight-recorder shards (the always-on post-mortem ring dumps of
``mxnet_tpu._debug.flightrec`` — ISSUE 8) merge the same way: they
carry the same rank/pid and timebase, so a crash dump interleaves with
the live shards of the surviving ranks on one timeline; every event
from a flight-record shard is tagged ``args.source = "flightrec"`` so
black-box evidence is distinguishable from live-profile evidence.

    python tools/trace_merge.py trace_rank0.json flightrec_r1_*.json \
        -o merged.json

``--no-align`` keeps raw per-rank timestamps (debugging the alignment
itself). Exit status is non-zero when: no input shards were given, the
shards contain zero events (writing an empty trace would hide the
mistake), or no flow pairs match while both sides emitted flows — the
signature of mismatched shards.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-rank chrome-trace shards (live profiler "
                    "dumps and/or flight-recorder post-mortems) into "
                    "one trace")
    ap.add_argument("shards", nargs="*",
                    help="per-rank trace JSON files (profiler.dump() "
                         "shards and/or flightrec_r*.json post-mortems)")
    ap.add_argument("-o", "--output", default="merged_trace.json",
                    help="merged trace path (default: %(default)s)")
    ap.add_argument("--no-align", action="store_true",
                    help="skip heartbeat-based clock alignment")
    args = ap.parse_args(argv)

    if not args.shards:
        print("error: no input shards — pass at least one trace file "
              "(a profiler.dump() shard or a flightrec_r*.json "
              "post-mortem); refusing to write an empty trace",
              file=sys.stderr)
        return 2

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu import base, profiler

    merged, summary = profiler.merge_traces(
        args.shards, output=None, align=not args.no_align)
    real_events = sum(1 for e in merged["traceEvents"]
                      if e.get("ph") != "M")
    if real_events == 0:
        print("error: the %d input shard(s) contain zero events — "
              "refusing to write an empty trace (was the profiler "
              "ever running / the flight recorder enabled?)"
              % len(args.shards), file=sys.stderr)
        return 1
    with base.atomic_write(args.output, "w") as f:
        json.dump(merged, f)
    print("merged %d shard(s) (ranks %s, %d flight-recorder) -> %s: "
          "%d events"
          % (len(args.shards), summary["ranks"],
             summary["flightrec_shards"], args.output,
             summary["events"]))
    for rank, off in sorted(summary["offsets_us"].items()):
        print("  rank %s: clock offset %+.1f us" % (rank, off))
    print("  flow events: %d started, %d finished, %d paired"
          % (summary["flows_started"], summary["flows_finished"],
             summary["flows_paired"]))
    if summary["flows_started"] and summary["flows_finished"] \
            and not summary["flows_paired"]:
        print("error: no client/server flow pair matched — are these "
              "shards from the same run?", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
