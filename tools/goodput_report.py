#!/usr/bin/env python
"""Render and compare run-level goodput manifests (ISSUE 14).

The manifests come from ``mxnet_tpu._debug.goodput`` — every
``elastic_train_loop`` run (and every ``bench.py`` BENCH_MODEL gate)
publishes one under ``$MXTPU_RUNS_DIR/<run_id>/manifest.json``. This
tool is deliberately dependency-free (stdlib json only, no jax import):
it must run on a laptop against manifests rsync'd off a fleet.

Usage::

    python tools/goodput_report.py RUN            # human-readable report
    python tools/goodput_report.py --compare A B  # regression verdict

``RUN``/``A``/``B`` are manifest paths or run directories containing
``manifest.json``. ``--compare`` treats A as the baseline and B as the
candidate, and exits non-zero when B regresses past threshold — the
machine-checkable perf trajectory across runs and bench rounds.

The verdict is noise-robust by construction: the step-time check uses
the run's MEDIAN step time (p50 from the log-bucketed histogram, not
the mean a single straggler can drag), and every check requires BOTH a
relative threshold and an absolute floor to fire — a 30% swing on a
3us microbench step or a 0.1s blip in a category can never page
anyone. Thresholds: ``--step-pct`` (default 25: median step-time
growth %), ``--min-step-abs-us`` (50), ``--ratio-drop`` (0.05:
goodput-ratio points), ``--category-pct`` (5: badput-category share
growth in points of wall), ``--min-abs-s`` (0.25).

Exit codes: 0 = no regression, 1 = regression past threshold,
2 = bad usage / unreadable manifest.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# keep in sync with mxnet_tpu/_debug/goodput.py (not imported: this
# tool must not drag the jax runtime in)
SCHEMA = "mxtpu.goodput.run/1"
CATEGORIES = ("compute", "compile", "input_wait", "checkpoint",
              "recovery", "rewind_replay", "host_overhead", "idle")
# categories whose GROWTH is badput (compute growing is fine)
BADPUT = tuple(c for c in CATEGORIES if c != "compute")


def load_manifest(path):
    if os.path.isdir(path):
        path = os.path.join(path, "manifest.json")
    with open(path, encoding="utf-8") as f:
        m = json.load(f)
    if m.get("schema") != SCHEMA:
        raise ValueError("%s: schema %r is not %r (not a goodput run "
                         "manifest)" % (path, m.get("schema"), SCHEMA))
    return m


def _fmt_s(s):
    return "%.3fs" % s if s < 120 else "%dm%04.1fs" % divmod(s, 60)


def render(m):
    """One manifest -> a human-readable report (list of lines)."""
    lines = []
    wall = float(m.get("wall_s") or 0.0)
    lines.append("goodput run %s  [%s]" % (m["run_id"],
                                           m.get("outcome", "open")))
    env = m.get("env", {})
    lines.append("  rank=%s world=%s mesh=%s" % (
        env.get("rank"), env.get("world"), env.get("mesh")))
    toks = env.get("signature_tokens") or {}
    if toks:
        lines.append("  signature tokens: " + " ".join(
            "%s=%s" % (k, toks[k]) for k in sorted(toks)))
    lines.append("  wall %s   goodput ratio %.4f" % (
        _fmt_s(wall), float(m.get("goodput_ratio") or 0.0)))
    lines.append("  %-16s %12s %8s" % ("category", "seconds", "share"))
    cats = m.get("categories_s", {})
    for c in CATEGORIES:
        s = float(cats.get(c, 0.0))
        lines.append("  %-16s %12.3f %7.1f%%" % (
            c, s, 100.0 * s / wall if wall > 0 else 0.0))
    st = m.get("steps", {})
    t = st.get("time_s")
    if t:
        lines.append(
            "  steps %d (warmup %d, replayed %d, fallback %d): "
            "p50 %.6fs  p95 %.6fs  p99 %.6fs  mean %.6fs" % (
                st.get("count", 0), st.get("warmup", 0),
                st.get("replayed", 0), st.get("fallback", 0),
                t["p50"], t["p95"], t["p99"], t["mean"]))
    cn = m.get("counters", {})
    if any(cn.values()):
        lines.append("  " + " ".join("%s=%s" % (k, cn[k])
                                     for k in sorted(cn) if cn[k]))
    for ev in m.get("events", [])[:20]:
        detail = " ".join("%s=%s" % (k, ev[k]) for k in sorted(ev)
                          if k not in ("t_s", "kind"))
        lines.append("  event +%8.3fs %-14s %s" % (
            ev.get("t_s", 0.0), ev.get("kind", "?"), detail))
    bench = m.get("bench")
    if bench:
        lines.append("  bench model=%s gate_ok=%s" % (
            bench.get("model"),
            (bench.get("result", {}).get("gate") or {}).get("ok")))
    return lines


def _p50(m):
    t = m.get("steps", {}).get("time_s")
    return float(t["p50"]) if t and t.get("p50") else None


def compare(a, b, step_pct=25.0, min_step_abs_us=50.0,
            ratio_drop=0.05, category_pct=5.0, min_abs_s=0.25):
    """Regression verdict for candidate ``b`` against baseline ``a``.
    Returns (lines, regressed: bool)."""
    lines = ["baseline  %s  [%s]" % (a["run_id"],
                                     a.get("outcome", "?")),
             "candidate %s  [%s]" % (b["run_id"],
                                     b.get("outcome", "?"))]
    regressed = False

    # 1) median step time — the core cross-run/bench-round number
    pa, pb = _p50(a), _p50(b)
    if pa and pb:
        rel = 100.0 * (pb - pa) / pa
        bad = rel > step_pct and (pb - pa) * 1e6 > min_step_abs_us
        regressed |= bad
        lines.append(
            "%-11s median step time: %.6fs -> %.6fs (%+.1f%%; "
            "threshold +%.0f%% and +%.0fus)" % (
                "REGRESSION" if bad else "ok", pa, pb, rel, step_pct,
                min_step_abs_us))
    else:
        lines.append("skip        median step time: missing in %s" % (
            "both" if not (pa or pb) else
            ("baseline" if not pa else "candidate")))

    # 2) goodput-ratio drop
    ra = float(a.get("goodput_ratio") or 0.0)
    rb = float(b.get("goodput_ratio") or 0.0)
    wa = float(a.get("wall_s") or 0.0)
    wb = float(b.get("wall_s") or 0.0)
    if wa > 0 and wb > 0:
        drop = ra - rb
        bad = drop > ratio_drop
        regressed |= bad
        lines.append(
            "%-11s goodput ratio: %.4f -> %.4f (%+.4f; threshold "
            "-%.2f)" % ("REGRESSION" if bad else "ok", ra, rb, -drop,
                        ratio_drop))

    # 3) per-category drift (badput categories only — compute growing
    #    is the point of the exercise)
    ca = a.get("categories_s", {})
    cb = b.get("categories_s", {})
    for c in BADPUT:
        sa, sb = float(ca.get(c, 0.0)), float(cb.get(c, 0.0))
        if wa <= 0 or wb <= 0 or (sa == 0 and sb == 0):
            continue
        drift_pp = 100.0 * (sb / wb - sa / wa)
        grew_s = sb - sa
        bad = drift_pp > category_pct and grew_s > min_abs_s
        regressed |= bad
        mark = "REGRESSION" if bad else (
            "drift" if abs(drift_pp) > 0.5 else "ok")
        lines.append(
            "%-11s %-14s %8.3fs (%5.1f%%) -> %8.3fs (%5.1f%%)  "
            "%+0.1fpp" % (mark, c, sa,
                          100.0 * sa / wa, sb, 100.0 * sb / wb,
                          drift_pp))

    lines.append("verdict: %s" % ("REGRESSION" if regressed else
                                  "no regression"))
    return lines, regressed


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="goodput_report",
        description="Render / compare run-level goodput manifests.")
    ap.add_argument("runs", nargs="+",
                    help="manifest path(s) or run director(ies)")
    ap.add_argument("--compare", action="store_true",
                    help="compare two runs: baseline candidate")
    ap.add_argument("--step-pct", type=float, default=25.0)
    ap.add_argument("--min-step-abs-us", type=float, default=50.0)
    ap.add_argument("--ratio-drop", type=float, default=0.05)
    ap.add_argument("--category-pct", type=float, default=5.0)
    ap.add_argument("--min-abs-s", type=float, default=0.25)
    args = ap.parse_args(argv)
    try:
        manifests = [load_manifest(p) for p in args.runs]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("goodput_report: %s" % e, file=sys.stderr)
        return 2
    if args.compare:
        if len(manifests) != 2:
            print("goodput_report: --compare takes exactly two runs "
                  "(baseline candidate)", file=sys.stderr)
            return 2
        lines, regressed = compare(
            manifests[0], manifests[1], step_pct=args.step_pct,
            min_step_abs_us=args.min_step_abs_us,
            ratio_drop=args.ratio_drop,
            category_pct=args.category_pct, min_abs_s=args.min_abs_s)
        print("\n".join(lines))
        return 1 if regressed else 0
    for m in manifests:
        print("\n".join(render(m)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
