#!/usr/bin/env python
"""Re-run a test many times with different seeds to surface flakiness
(ref: tools/flakiness_checker.py).

Usage:
    python tools/flakiness_checker.py tests/test_rnn.py::test_gradients_flow
    python tools/flakiness_checker.py -n 50 tests/test_operator.py
"""
from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="flakiness checker (ref: tools/flakiness_checker.py)")
    parser.add_argument("test", help="pytest target (file or file::test)")
    parser.add_argument("-n", "--num-trials", type=int, default=20)
    parser.add_argument("-s", "--seed", type=int, default=None,
                        help="fixed seed; default draws a new one per trial")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    failures = []
    for trial in range(args.num_trials):
        seed = args.seed if args.seed is not None else \
            random.randint(0, 2 ** 31 - 1)
        env = dict(os.environ)
        env["MXNET_TEST_SEED"] = str(seed)
        cmd = [sys.executable, "-m", "pytest", args.test, "-q", "-x"]
        res = subprocess.run(cmd, env=env, capture_output=not args.verbose)
        status = "PASS" if res.returncode == 0 else "FAIL"
        print("trial %3d seed %10d : %s" % (trial, seed, status))
        if res.returncode != 0:
            failures.append(seed)
    print("\n%d/%d trials failed" % (len(failures), args.num_trials))
    if failures:
        print("failing seeds (reproduce with MXNET_TEST_SEED=<seed>):")
        for s in failures:
            print("  ", s)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
