"""kvstore / collective bandwidth measurement.

ref: /root/reference/tools/bandwidth/measure.py — times push+pull of
model-sized gradient arrays through a kvstore and reports effective
algorithm bandwidth, backing scaling-efficiency claims with numbers.

TPU-native differences: the transport under kvstore is XLA collectives
over the device mesh (psum on ICI) instead of PCIe/NCCL reduce trees,
so this tool also measures the raw mesh allreduce (`--mode mesh`) the
kvstore rides on. Emits ONE JSON line per size, like bench.py:
  {"metric": "kvstore_pushpull_bandwidth", "size_mb": N,
   "gb_per_sec": N, ...}

Usage:
  python tools/bandwidth/measure.py                    # kvstore mode
  python tools/bandwidth/measure.py --mode mesh        # raw psum
  python tools/launch.py -n 4 python tools/bandwidth/measure.py \
      --kv-store dist_sync                             # multi-process
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))


def parse_args():
    p = argparse.ArgumentParser(description="kvstore bandwidth benchmark "
                                "(ref: tools/bandwidth/measure.py)")
    p.add_argument("--kv-store", type=str, default="local",
                   help="kvstore type: local / device / dist_sync")
    p.add_argument("--mode", type=str, default="kvstore",
                   choices=["kvstore", "mesh"],
                   help="kvstore push/pull, or raw mesh psum")
    p.add_argument("--sizes-mb", type=str, default="1,4,16,64",
                   help="comma-separated tensor sizes in MB")
    p.add_argument("--num-batches", type=int, default=10)
    p.add_argument("--test-results", type=int, default=1,
                   help="verify aggregation numerics like the reference")
    return p.parse_args()


def measure_kvstore(args):
    import numpy as np
    import mxnet_tpu as mx

    if args.kv_store.startswith("dist") and "MXTPU_COORDINATOR" in \
            os.environ:
        import jax
        jax.distributed.initialize(os.environ["MXTPU_COORDINATOR"],
                                   int(os.environ["MXTPU_NUM_PROCS"]),
                                   int(os.environ["MXTPU_PROC_ID"]))
    kv = mx.kv.create(args.kv_store)
    results = []
    for size_mb in [float(s) for s in args.sizes_mb.split(",")]:
        n = int(size_mb * 1024 * 1024 / 4)
        val = mx.nd.ones((n,))
        kv.init(str(int(size_mb * 1000)), mx.nd.zeros((n,)))
        out = mx.nd.zeros((n,))
        key = str(int(size_mb * 1000))
        kv.pushpull(key, val, out=out)         # warm
        float(out.asnumpy()[0])
        t0 = time.perf_counter()
        for _ in range(args.num_batches):
            kv.pushpull(key, val, out=out)
        s = float(out.asnumpy()[0])            # sync
        dt = (time.perf_counter() - t0) / args.num_batches
        if args.test_results:
            # each pushpull round replaces the store with the cross-worker
            # sum of ones (no server optimizer attached)
            want = kv.num_workers
            assert s == want, "aggregation error: got %s want %s" % (
                s, want)
        # algorithm bandwidth: bytes through the reduce per second
        gbps = size_mb / 1024.0 / dt
        rec = {"metric": "kvstore_pushpull_bandwidth",
               "kv_store": args.kv_store, "size_mb": size_mb,
               "ms_per_round": round(dt * 1e3, 3),
               "gb_per_sec": round(gbps, 3),
               "num_workers": kv.num_workers, "rank": kv.rank}
        results.append(rec)
        if kv.rank == 0:
            print(json.dumps(rec))
    return results


def measure_mesh(args):
    """Raw allreduce over the device mesh — the ICI-collective floor the
    kvstore path cannot beat."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    import numpy as np

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("dp",))
    results = []
    for size_mb in [float(s) for s in args.sizes_mb.split(",")]:
        n = int(size_mb * 1024 * 1024 / 4 / len(devs)) * len(devs)
        x = jnp.ones((n,), jnp.float32)

        @jax.jit
        def allreduce(v):
            f = shard_map(lambda s: jax.lax.psum(s, "dp"), mesh=mesh,
                          in_specs=P("dp"), out_specs=P("dp"))
            return f(v)

        y = allreduce(x)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        it = args.num_batches
        for _ in range(it):
            y = allreduce(y * 0 + 1.0)
        jax.block_until_ready(y)
        dt = (time.perf_counter() - t0) / it
        rec = {"metric": "mesh_allreduce_bandwidth",
               "devices": len(devs), "size_mb": size_mb,
               "ms_per_round": round(dt * 1e3, 3),
               "gb_per_sec": round(size_mb / 1024.0 / dt, 3)}
        results.append(rec)
        print(json.dumps(rec))
    return results


if __name__ == "__main__":
    a = parse_args()
    if a.mode == "mesh":
        measure_mesh(a)
    else:
        measure_kvstore(a)
