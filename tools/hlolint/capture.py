"""hlolint artifact capture: the bridge from compiled programs to rules.

An **artifact** is one plain dict — picklable, executable-free:

    {"name": "fused_step",            # compiling subsystem
     "sig":  "fused_step:59ea9d0e",   # the roofline join key
     "hlo":  "<compiled.as_text()>",  # optimized HLO text
     "meta": {...}}                   # the contract, see below

The ``meta`` contract (producers: ``FusedTrainStep._capture_program``;
every key optional — a missing key disables the rule that reads it):

* ``donated`` — tuple of flat entry-parameter numbers the builder
  donated (H001 requires each in the input-output alias map).
* ``plan`` — {collective kind: analytic payload bytes} for one step
  (H002; the 4-bytes-per-trainable-param gradient all-reduce model the
  BENCH_MODEL=gspmd_step gate validated at <1% wire error).
* ``replicated_slots`` — top-level output tuple indices pinned ``P()``
  (H003: loss=0, aux=4, health=5 in the GSPMD fused step).
* ``out_specs`` — per top-level output slot, the list of partition-
  spec tuples the executable actually carries (H003's measured side;
  extracted eagerly from ``compiled.output_shardings`` at capture so
  no artifact pins device state).
* ``dtype`` — dominant trainable-param dtype key (``bf16``/``f32``/
  ...); H004 activates only on declared-low-precision programs.
* ``mesh`` — axis-name -> size dict, for reports.
* ``gspmd`` — True for the one-GSPMD-program step mode.

The capture sources: :func:`from_profiler` drains the compile
registry's program store (``profiler.record_program``, fed by every
fused-step AOT compile — tier-1 dryruns make every signature
analyzable with no new lowering work), and :func:`dryrun_programs`
runs the built-in three-mesh CPU dryrun (dp8, dp4xtp2, dp2xtp2xsp2 —
the standing BENCH_MODEL=gspmd_step configs) to produce them on
demand for the CLI and the bench gate.
"""
from __future__ import annotations

import os

_COMM_MODEL_UNSET = object()
_COMM_MODEL = _COMM_MODEL_UNSET


def load_comm_model():
    """benchmark/comm_model.py as a module (it lives outside the
    package tree; same file-location import the fused step uses), or
    None when unavailable."""
    global _COMM_MODEL
    if _COMM_MODEL is _COMM_MODEL_UNSET:
        try:
            import importlib.util
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "benchmark", "comm_model.py")
            spec = importlib.util.spec_from_file_location(
                "_hlolint_comm_model", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _COMM_MODEL = mod
        except Exception:
            _COMM_MODEL = None
    return _COMM_MODEL


def make_artifact(name, sig, hlo, meta=None):
    """Normalize one program into the artifact shape rules consume."""
    return {"name": str(name), "sig": str(sig), "hlo": str(hlo or ""),
            "meta": dict(meta or {})}


def from_profiler(name=None):
    """Artifacts from the profiler's program store (oldest first)."""
    from mxnet_tpu import profiler
    return [make_artifact(r["name"], r["sig"], r["hlo"], r["meta"])
            for r in profiler.program_records(name)]


# the standing mesh configs every sharded-step gate exercises
DRYRUN_MESHES = (
    ("dp8", None),                       # manual-dp shard_map mode
    ("dp4_tp2", {"dp": 4, "tp": 2}),     # GSPMD, model-parallel
    ("dp2_tp2_sp2", {"dp": 2, "tp": 2, "sp": 2}),  # 3D
)


def _dryrun_one(mesh, steps=4, seed=0):
    """One tiny fused-step training net on ``mesh`` (the
    BENCH_MODEL=gspmd_step harness): enough steps to pass warming so
    the signature compiles and the AOT capture records its program."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    rs = onp.random.RandomState(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=12))
    net.add(nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    for _, p in sorted(net.collect_params().items()):
        p.set_data(mx.nd.array(
            rs.randn(*p.shape).astype(onp.float32) * 0.1))
    loss = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.fuse_step(lambda xx, yy: loss(net(xx), yy), mesh=mesh,
                        bucket_bytes=512)
    data = onp.random.RandomState(7)
    for _ in range(steps):
        x = mx.nd.array(data.rand(8, 12).astype(onp.float32))
        y = mx.nd.array(data.rand(8, 4).astype(onp.float32))
        step(x, y, batch_size=8)
    return step


def dryrun_programs(configs=DRYRUN_MESHES, repeat_first=False):
    """Run the built-in CPU dryrun over ``configs`` (name, axes-dict —
    None = first 8 devices, manual dp) and return the artifacts it
    captured. ``repeat_first=True`` builds the first config's step a
    second time so its signature has two lowerings and H005 checks a
    real group, not a singleton. Requires the 8-device virtual CPU
    platform (tools.launch.force_virtual_cpu_devices)."""
    from tools.launch import force_virtual_cpu_devices
    force_virtual_cpu_devices(8)
    import jax
    from mxnet_tpu import profiler
    from mxnet_tpu.parallel import create_mesh

    # Select "captured after this point" by the store's monotonic seq,
    # not a list index — the _PROGRAM_CAP ring trims the front, so an
    # index snapshot goes stale whenever earlier runs filled the store.
    before_seq = max((r.get("seq", -1)
                      for r in profiler.program_records()), default=-1)
    for i, (name_, axes) in enumerate(configs):
        mesh = create_mesh(devices=jax.devices()[:8]) if axes is None \
            else create_mesh(**axes)
        _dryrun_one(mesh)
        if repeat_first and i == 0:
            _dryrun_one(mesh)
    return [make_artifact(r["name"], r["sig"], r["hlo"], r["meta"])
            for r in profiler.program_records()
            if r.get("seq", -1) > before_seq]
