"""hlolint driver: analyze captured program artifacts, report, exit.

Same reporting contract as mxlint (tools/lintcommon.py): numbered
findings, a JSON baseline of known exemptions
(``tools/hlolint/baseline.json`` — empty on a clean tree), text /
GitHub-annotation / ``--json`` output, exit 1 on findings. One
difference by design: there are no inline waiver comments — an HLO
dump has no reviewable source line to annotate — so the baseline file
is the ONLY exemption mechanism, which keeps every exemption in one
diff-visible place.

Exit codes: 0 clean, 1 findings, 2 nothing to analyze (an empty
capture must fail CI loudly — a gate that analyzed zero programs
proves nothing).
"""
from __future__ import annotations

import json
import os
import sys
import time

from tools import lintcommon as _common
from tools.hlolint.rules import ALL_RULES

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def load_baseline(path=BASELINE_PATH):
    return _common.load_baseline(path)


def write_baseline(findings, path=BASELINE_PATH):
    _common.write_baseline(
        findings, path,
        "Known findings exempt from failing hlolint. Keep empty; see "
        "docs/LINTING.md (HLO contracts).")


def run(artifacts, rules=None, baseline=None):
    """Check every artifact against the per-artifact rules and every
    same-``sig`` group against the group rules (H005). Returns
    ``(kept findings, n_baselined, per_sig_seconds)`` — the timing dict
    backs the BENCH_MODEL=hlolint <5 s/signature assertion."""
    rules = list(ALL_RULES if rules is None else rules)
    if baseline is None:
        baseline = load_baseline()
    base_keys = _common.baseline_keys(baseline)

    groups = {}
    for art in artifacts:
        groups.setdefault(art["sig"], []).append(art)

    findings = []
    per_sig = {}
    for sig in sorted(groups):
        t0 = time.perf_counter()
        for rule in rules:
            if getattr(rule, "group", False):
                findings.extend(rule.check_group(sig, groups[sig]))
            else:
                for art in groups[sig]:
                    findings.extend(rule.check(art))
        per_sig[sig] = time.perf_counter() - t0

    kept, _n_waived, n_baselined = _common.apply_waivers_and_baseline(
        findings, {}, base_keys)
    return kept, n_baselined, per_sig


def report(artifacts, findings, n_baselined, per_sig):
    """JSON-safe result record — the ``--json`` body and the
    BENCH_MODEL=hlolint manifest payload."""
    return {
        "programs": sorted(
            {a["sig"]: {"sig": a["sig"], "name": a["name"],
                        "mesh": a["meta"].get("mesh"),
                        "gspmd": a["meta"].get("gspmd"),
                        "lowerings": sum(
                            1 for b in artifacts
                            if b["sig"] == a["sig"])}
             for a in artifacts}.values(),
            key=lambda p: p["sig"]),
        "findings": [{"code": f.code, "path": f.path, "line": f.line,
                      "message": f.message} for f in findings],
        "n_baselined": n_baselined,
        "per_sig_seconds": {s: round(t, 4)
                            for s, t in per_sig.items()},
        "max_sig_seconds": round(max(per_sig.values()), 4)
        if per_sig else 0.0,
    }


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m tools.hlolint",
        description="Static contract verification of compiled "
                    "programs (docs/LINTING.md, 'HLO contracts').")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to specific rule codes (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--format", choices=("text", "github"),
                    default="text",
                    help="finding output format (github = ::error "
                         "workflow annotations)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline")
    ap.add_argument("--from-profiler", action="store_true",
                    help="analyze programs already captured in this "
                         "process instead of running the built-in "
                         "three-mesh dryrun")
    args = ap.parse_args(argv)

    from tools.hlolint import capture
    if args.from_profiler:
        artifacts = capture.from_profiler()
    else:
        # the built-in capture: fused-step dryruns on the standing
        # three mesh configs, first config lowered twice so H005
        # checks a genuine re-lowering group
        artifacts = capture.dryrun_programs(repeat_first=True)
    if not artifacts:
        print("hlolint: no program artifacts captured — nothing to "
              "analyze", file=sys.stderr)
        return 2

    rules = None
    if args.rule:
        want = set(args.rule)
        rules = [r for r in ALL_RULES if r.code in want]
    findings, n_baselined, per_sig = run(artifacts, rules=rules)

    if args.write_baseline:
        write_baseline(findings)
        print("baseline: recorded %d findings" % len(findings))
        return 0

    if args.json:
        print(json.dumps(report(artifacts, findings, n_baselined,
                                per_sig), indent=2, sort_keys=True))
    else:
        _common.emit(findings, args.format, "hlolint")
    print("hlolint: %d program%s (%d signature%s), %d finding%s "
          "(%d baselined)" % (
              len(artifacts), "" if len(artifacts) == 1 else "s",
              len(per_sig), "" if len(per_sig) == 1 else "s",
              len(findings), "" if len(findings) == 1 else "s",
              n_baselined), file=sys.stderr)
    return 1 if findings else 0
