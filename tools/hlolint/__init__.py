"""hlolint: static contract verification of compiled programs.

mxlint (tools/mxlint) analyzes the PYTHON layer; the invariants the
GSPMD fused step (PR 16) and the roofline plane (PR 17) rest on live
one layer down, in the XLA artifacts — where a regression becomes a
2x HBM footprint (dropped donation), a phantom reshard (unplanned
all-gather), a read-time cross-process gather (sharded loss), a
half-rate MXU (silent f32 upcast), or a cluster hang (nondeterministic
collective order). hlolint checks those five contracts (H001-H005,
tools/hlolint/rules.py) over the program artifacts every fused-step
AOT compile hands to ``profiler.record_program`` — so each tier-1
dryrun signature is analyzable with no new lowering work.

    python -m tools.hlolint          # three-mesh dryrun + analyze
    python -m tools.hlolint --json   # machine output for CI
    python -m tools.hlolint --rule H002 --from-profiler

Shares the mxlint reporting core (tools/lintcommon.py): numbered
rules, empty checked-in baseline (tools/hlolint/baseline.json), exit
1 on findings (2 when nothing was captured). See docs/LINTING.md,
"HLO contracts (H-rules)". tests/test_hlolint.py pins each rule with
a deliberately contract-breaking program and runs the real three-mesh
end-to-end clean check in tier-1.
"""
from .capture import dryrun_programs, from_profiler, make_artifact
from .core import load_baseline, main, report, run
from .rules import ALL_RULES, Finding

__all__ = ["ALL_RULES", "Finding", "run", "main", "report",
           "load_baseline", "make_artifact", "from_profiler",
           "dryrun_programs"]
