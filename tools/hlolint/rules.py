"""hlolint rules: contract checks over compiled-program artifacts.

Each rule checks one invariant of a captured program (the ``{name,
sig, hlo, meta}`` records ``profiler.record_program`` accumulates —
see tools/hlolint/capture.py for the meta key contract):

* **H001 donation-took** — every donated argument (``meta['donated']``,
  flat entry-parameter numbers) appears in the program's
  ``input_output_alias`` map. XLA silently DROPS an alias it cannot
  honor (shape/dtype mismatch between the donated input and any
  output), and a donated-but-copied buffer is a 2x HBM regression the
  memory ledger only notices after OOM.
* **H002 collective inventory** — the per-kind collective payload
  (``comm_model.collect_hlo_inventory``) matches the analytic plan
  (``meta['plan']``): the gradient all-reduce within 1%, every other
  kind at zero (beneath a small absolute floor for bookkeeping ops).
  Any all-gather/all-to-all/collective-permute outside the plan is a
  phantom reshard.
* **H003 replicated outputs** — the output slots the builder pinned
  ``P()`` (``meta['replicated_slots']``: loss/aux/health) carry empty
  partition specs in the executable (``meta['out_specs']``). A
  sharded loss means a cross-process gather hides at read time.
* **H004 dtype discipline** — on a declared-bf16/f16 program
  (``meta['dtype']``), no f32 ``convert`` of a low-precision value
  feeds a ``dot``/``convolution``: a silent upcast runs the MXU at
  half rate and doubles the activation footprint.
* **H005 collective-order determinism** — re-lowerings of the same
  signature (artifacts sharing ``sig``) emit the identical ordered
  collective sequence. Cross-rank collective-order mismatch is a
  cluster hang, not a test failure, so it must die here.

Rules are static text/metadata analysis only — no JAX import, no
device work — so analysis stays cheap (the BENCH_MODEL=hlolint gate
prices it under 5 s per signature with huge margin).
"""
from __future__ import annotations

import re

from tools.lintcommon import Finding

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

# -- HLO text parsing helpers ------------------------------------------------


def alias_param_numbers(hlo):
    """Entry-parameter numbers appearing as alias sources in the
    HloModule header's ``input_output_alias={ {out_idx}: (param, {},
    may-alias), ... }`` map (empty set when the header has none)."""
    i = hlo.find("input_output_alias={")
    if i < 0:
        return set()
    s = hlo[i + len("input_output_alias="):]
    depth = 0
    blob = ""
    for j, ch in enumerate(s):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                blob = s[:j + 1]
                break
    return {int(m.group(1)) for m in re.finditer(
        r"\((\d+),\s*\{[^}]*\},\s*(?:may|must)-alias\)", blob)}


_COLL_RE = re.compile(r"=\s+(\(.*?\)|\S+)\s+(%s)(-start)?\("
                      % "|".join(_COLLECTIVES))


def collective_sequence(hlo):
    """Ordered ``(kind, result shape, lineno)`` of every collective
    instruction, top to bottom — the H005 determinism witness. Layout
    annotations are stripped (same program, same layout; the sequence
    identity that matters cross-rank is kind+shape+order)."""
    seq = []
    for n, line in enumerate(hlo.splitlines(), start=1):
        m = _COLL_RE.search(line)
        if m and "-done" not in line.split("=", 1)[-1][:60]:
            shape = re.sub(r"\{[^}]*\}", "", m.group(1))
            seq.append((m.group(2), shape, n))
    return seq


_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"([a-z0-9]+)\[[^\]]*\]\S*\s+([a-z0-9\-]+)\(([^)]*)\)")
_OPERAND_RE = re.compile(r"%?([A-Za-z_][\w.\-]*)")
_DTYPE_TOKENS = frozenset(
    ("f64", "f32", "f16", "bf16", "f8e4m3fn", "f8e5m2", "s32", "s64",
     "s16", "s8", "u32", "u64", "u16", "u8", "pred", "c64", "c128"))


def instruction_defs(hlo):
    """{name: (result dtype, opcode, operand names, lineno)} over every
    computation in the module. Operand tokens that are dtype keywords
    (the ``f32[8,16] %x`` long operand form) are dropped. Names are
    module-global here; HLO uniquifies across computations with ``.N``
    suffixes, which is exact enough for the def-use chains H004 walks."""
    defs = {}
    for n, line in enumerate(hlo.splitlines(), start=1):
        m = _DEF_RE.match(line)
        if not m:
            continue
        name, dtype, op, rands = m.groups()
        operands = [t for t in _OPERAND_RE.findall(rands)
                    if t not in _DTYPE_TOKENS]
        defs[name] = (dtype, op, operands, n)
    return defs


# -- rules -------------------------------------------------------------------

class H001DonationTook:
    code = "H001"
    summary = "every donated argument aliases an output buffer"

    def check(self, art):
        donated = tuple(art["meta"].get("donated") or ())
        if not donated:
            return []
        aliased = alias_param_numbers(art["hlo"])
        return [Finding(
            self.code, art["sig"], 1,
            "donated argument %d is NOT in the input-output alias map "
            "— XLA dropped the donation (likely an output shape/dtype "
            "mismatch) and the buffer is silently copied, a 2x HBM "
            "cost for this operand" % p)
            for p in donated if p not in aliased]


class H002CollectiveInventory:
    code = "H002"
    summary = "collective payload matches the analytic plan per kind"
    # planned kinds tolerate 1% modeling error; unplanned kinds allow a
    # small absolute floor (sub-page bookkeeping ops: loss gathers,
    # health sentinels) before they count as a phantom reshard
    REL_TOL = 0.01
    ABS_FLOOR = 4096

    def check(self, art):
        plan = art["meta"].get("plan")
        if plan is None:
            return []
        from tools.hlolint.capture import load_comm_model
        cm = load_comm_model()
        if cm is None:
            return [Finding(self.code, art["sig"], 1,
                            "benchmark/comm_model.py unavailable — "
                            "collective inventory not verifiable")]
        inv = cm.collect_hlo_inventory(art["hlo"])
        out = []
        if inv["unresolved_loops"]:
            out.append(Finding(
                self.code, art["sig"], 1,
                "%d loop(s) with unresolved trip counts — collective "
                "bytes under-counted, inventory not certifiable"
                % inv["unresolved_loops"]))
        for kind in sorted(set(plan) | set(inv["bytes_by_kind"])):
            measured = int(inv["bytes_by_kind"].get(kind, 0))
            planned = int(plan.get(kind, 0))
            tol = max(self.REL_TOL * planned, self.ABS_FLOOR) \
                if planned else self.ABS_FLOOR
            if abs(measured - planned) > tol:
                out.append(Finding(
                    self.code, art["sig"], 1,
                    "%s payload %d B vs analytic plan %d B "
                    "(tolerance %d B): %s" % (
                        kind, measured, planned, int(tol),
                        "phantom resharding traffic outside the plan"
                        if measured > planned
                        else "planned reduction missing from the wire")))
        return out


class H003ReplicatedOutputs:
    code = "H003"
    summary = "loss/aux/health output slots stay replicated (P())"

    def check(self, art):
        slots = tuple(art["meta"].get("replicated_slots") or ())
        specs = art["meta"].get("out_specs")
        if not slots:
            return []
        if specs is None:
            return [Finding(
                self.code, art["sig"], 1,
                "program declares replicated output slots %r but "
                "carries no out_specs — sharding not verifiable"
                % (slots,))]
        out = []
        for slot in slots:
            if slot >= len(specs):
                out.append(Finding(
                    self.code, art["sig"], 1,
                    "declared replicated output slot %d is missing "
                    "from the program's %d output slots"
                    % (slot, len(specs))))
                continue
            for k, spec in enumerate(specs[slot]):
                if any(ax is not None for ax in spec):
                    out.append(Finding(
                        self.code, art["sig"], 1,
                        "output slot %d leaf %d is sharded %r but the "
                        "contract pins it P() — reading it forces a "
                        "cross-process gather" % (slot, k, spec)))
        return out


class H004DtypeDiscipline:
    code = "H004"
    summary = "no f32 upcast feeding a matmul on a bf16/f16 path"

    def check(self, art):
        if art["meta"].get("dtype") not in ("bf16", "f16"):
            return []
        defs = instruction_defs(art["hlo"])
        low = ("bf16", "f16")
        out = []
        for name, (dtype, op, operands, lineno) in defs.items():
            if op not in ("dot", "convolution"):
                continue
            for rand in operands:
                rdef = defs.get(rand)
                if rdef is None or rdef[1] != "convert" \
                        or rdef[0] != "f32":
                    continue
                src = defs.get(rdef[2][0]) if rdef[2] else None
                if src is not None and src[0] in low:
                    out.append(Finding(
                        self.code, art["sig"], lineno,
                        "%s %s consumes f32 convert %s of a %s value "
                        "— silent upcast on a declared-%s path (half "
                        "MXU rate, 2x activation bytes)" % (
                            op, name, rand, src[0],
                            art["meta"]["dtype"])))
        return out


class H005CollectiveOrder:
    code = "H005"
    summary = "identical collective order across re-lowerings"
    group = True  # checks all artifacts sharing one signature

    def check_group(self, sig, arts):
        if len(arts) < 2:
            return []
        ref = collective_sequence(arts[0]["hlo"])
        ref_key = [(k, s) for k, s, _ in ref]
        out = []
        for i, art in enumerate(arts[1:], start=1):
            seq = collective_sequence(art["hlo"])
            key = [(k, s) for k, s, _ in seq]
            if key == ref_key:
                continue
            # first divergence point, for the message
            j = 0
            while j < min(len(key), len(ref_key)) \
                    and key[j] == ref_key[j]:
                j += 1
            here = "%s %s" % key[j] if j < len(key) else "<end>"
            there = "%s %s" % ref_key[j] if j < len(ref_key) else "<end>"
            line = seq[j][2] if j < len(seq) else 1
            out.append(Finding(
                self.code, sig, line,
                "re-lowering %d diverges from lowering 0 at "
                "collective %d: %s vs %s — nondeterministic collective "
                "order across ranks is a cluster hang" % (
                    i, j, here, there)))
        return out


ALL_RULES = (H001DonationTook(), H002CollectiveInventory(),
             H003ReplicatedOutputs(), H004DtypeDiscipline(),
             H005CollectiveOrder())
