#!/usr/bin/env python
"""Pack an image dataset into RecordIO (ref: tools/im2rec.py).

Two-phase workflow like the reference:
    python tools/im2rec.py --list prefix image_root     # write prefix.lst
    python tools/im2rec.py prefix image_root            # write .rec/.idx

List format (tab separated, identical to the reference):
    <index> \t <label> [\t more labels] \t <relative path>
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.recordio import (MXIndexedRecordIO, IRHeader,  # noqa: E402
                                pack, pack_img)

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive=True, exts=_EXTS):
    """Yield (relpath, label) with labels from sorted subdirectory names
    (ref: im2rec.py list_image)."""
    cat = {}
    if recursive:
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            for fname in sorted(files):
                if fname.lower().endswith(exts):
                    rel = os.path.relpath(os.path.join(path, fname), root)
                    folder = os.path.dirname(rel)
                    if folder not in cat:
                        cat[folder] = len(cat)
                    yield rel, cat[folder]
    else:
        for fname in sorted(os.listdir(root)):
            if fname.lower().endswith(exts):
                yield fname, 0


def write_list(prefix, root, args):
    entries = list(list_images(root, recursive=not args.no_recursive))
    if args.shuffle:
        random.Random(args.seed).shuffle(entries)
    with open(prefix + ".lst", "w") as f:
        for i, (rel, label) in enumerate(entries):
            f.write("%d\t%f\t%s\n" % (i, float(label), rel))
    return len(entries)


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def write_record(prefix, root, args):
    record = MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(prefix + ".lst"):
        label = labels[0] if len(labels) == 1 else labels
        header = IRHeader(0, label, idx, 0)
        path = os.path.join(root, rel)
        if args.pass_through:
            with open(path, "rb") as f:
                record.write_idx(idx, pack(header, f.read()))
        else:
            from PIL import Image
            img = Image.open(path).convert("RGB")
            if args.resize:
                w, h = img.size
                scale = args.resize / min(w, h)
                img = img.resize((int(w * scale), int(h * scale)))
            import numpy as np
            record.write_idx(idx, pack_img(header, np.asarray(img)[..., ::-1],
                                           quality=args.quality,
                                           img_fmt=args.encoding))
        count += 1
        if count % 1000 == 0:
            print("packed %d images" % count)
    record.close()
    return count


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Create a RecordIO image dataset (ref: tools/im2rec.py)")
    parser.add_argument("prefix", help="prefix of the .lst/.rec/.idx files")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true",
                        help="create the image list instead of the record")
    parser.add_argument("--no-recursive", action="store_true")
    parser.add_argument("--shuffle", dest="shuffle", action="store_true",
                        default=True,
                        help="shuffle the list (default; see --no-shuffle)")
    parser.add_argument("--no-shuffle", dest="shuffle", action="store_false")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", default=".jpg",
                        help=".jpg/.png re-encode, or .raw for "
                             "pre-decoded pixels (decode-free reads, "
                             "~13x file size; recordio.pack_raw_img)")
    parser.add_argument("--pass-through", action="store_true",
                        help="store raw file bytes without re-encoding")
    args = parser.parse_args(argv)
    if args.list:
        n = write_list(args.prefix, args.root, args)
        print("wrote %s.lst (%d entries)" % (args.prefix, n))
    else:
        if not os.path.exists(args.prefix + ".lst"):
            write_list(args.prefix, args.root, args)
        n = write_record(args.prefix, args.root, args)
        print("wrote %s.rec (%d records)" % (args.prefix, n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
