"""Shared lint-driver core for the repo's static analyzers.

Two analyzers share one reporting contract: ``tools.mxlint`` (Python/C
AST rules, MXnnn) and ``tools.hlolint`` (compiled-program artifact
rules, Hnnn). The pieces that define that contract — the
:class:`Finding` record, the waiver grammar, the JSON baseline and the
finding emitters with their exit-code semantics — live here so the two
tools cannot drift apart on what a waiver means or how CI parses a
finding.

Waiver idiom (the tool tag selects the analyzer):

    # mxlint: disable=MX003 (reason why this exemption is sound)
    # hlolint: disable=H002 (reason)

A waiver suppresses the listed codes on its own line and the line
directly below it; ``disable-file=`` waives for the whole file. A
waiver without a parenthesized justification is itself reported (the
tool's 000 code): the point is a reviewed reason next to every
exemption.

Baseline: a JSON file of ``{code, path, line}`` triples that don't
fail the run — the cpplint NOLINT-file escape hatch for bulk-adopting
a rule. Checked-in baselines stay empty on a clean tree.
"""
from __future__ import annotations

import json
import re


class Finding:
    __slots__ = ("code", "path", "line", "message", "extra_waiver_lines")

    def __init__(self, code, path, line, message,
                 extra_waiver_lines=()):
        self.code = code
        self.path = path
        self.line = line
        self.message = message
        # additional lines whose waivers also suppress this finding
        # (mxlint MX003: the container's definition line)
        self.extra_waiver_lines = tuple(extra_waiver_lines)

    def __repr__(self):
        return "%s:%d: %s %s" % (self.path, self.line, self.code,
                                 self.message)


def waiver_regexes(tool, code_re):
    """(line-waiver, file-waiver) regexes for a tool tag and code
    pattern (e.g. ``("mxlint", r"MX\\d{3}")``)."""
    codes = r"((?:%s)(?:\s*,\s*%s)*)" % (code_re, code_re)
    line = re.compile(r"(?:#|//)\s*%s:\s*disable=%s\s*(\(.+)?"
                      % (tool, codes))
    file_ = re.compile(r"(?:#|//)\s*%s:\s*disable-file=%s\s*(\(.+)?"
                       % (tool, codes))
    return line, file_


def parse_waivers(src, line_re, file_re):
    """(line waivers, file waivers, bad waivers). Line waivers are
    {line -> set(codes)}; a waiver covers its own line and the next
    one. Waivers lacking a justification are returned as bad
    ``(lineno, sorted codes)`` pairs."""
    waivers = {}
    file_waivers = set()
    bad = []
    for i, line in enumerate(src.splitlines(), start=1):
        fm = file_re.search(line)
        m = line_re.search(line) if fm is None else None
        if fm is not None:
            codes = {c.strip() for c in fm.group(1).split(",")}
            file_waivers.update(codes)
            reason = (fm.group(2) or "").strip("() \t")
        elif m is not None:
            codes = {c.strip() for c in m.group(1).split(",")}
            reason = (m.group(2) or "").strip("() \t")
            waivers.setdefault(i, set()).update(codes)
            waivers.setdefault(i + 1, set()).update(codes)
        else:
            continue
        if not reason:
            bad.append((i, sorted(codes)))
    return waivers, file_waivers, bad


def apply_waivers_and_baseline(findings, waiver_maps, base_keys):
    """Partition findings against per-file waivers and the baseline.

    ``waiver_maps``: {path -> (line waivers, file waivers)};
    ``base_keys``: set of (code, path, line) with line possibly None.
    Returns (kept findings sorted, n_waived, n_baselined)."""
    kept = []
    n_waived = n_baselined = 0
    for fi in findings:
        waivers, file_waivers = waiver_maps.get(fi.path, ({}, set()))
        lines = (fi.line,) + fi.extra_waiver_lines
        if fi.code in file_waivers or \
                any(fi.code in waivers.get(l, ()) for l in lines):
            n_waived += 1
        elif (fi.code, fi.path, fi.line) in base_keys or \
                (fi.code, fi.path, None) in base_keys:
            n_baselined += 1
        else:
            kept.append(fi)
    kept.sort(key=lambda f: (f.path, f.line, f.code))
    return kept, n_waived, n_baselined


def load_baseline(path):
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f).get("findings", [])
    except (OSError, ValueError):
        return []


def baseline_keys(baseline):
    return {(b["code"], b["path"], b.get("line")) for b in baseline}


def write_baseline(findings, path, comment):
    data = {
        "comment": comment,
        "findings": [{"code": f.code, "path": f.path, "line": f.line}
                     for f in findings],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def emit(findings, fmt, tool):
    for f in findings:
        if fmt == "github":
            # GitHub Actions annotation syntax: shows inline on the PR
            print("::error file=%s,line=%d,title=%s %s::%s"
                  % (f.path, f.line, tool, f.code, f.message))
        else:
            print("%s:%d: %s %s" % (f.path, f.line, f.code, f.message))


def summary_line(tool, findings, n_waived, n_baselined, bad):
    s = "%s: %d finding%s (%d waived, %d baselined)" % (
        tool, len(findings), "" if len(findings) == 1 else "s",
        n_waived, n_baselined)
    if bad:
        s += ", %d bad waiver%s" % (len(bad),
                                    "" if len(bad) == 1 else "s")
    return s
