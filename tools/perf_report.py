#!/usr/bin/env python
"""Render and compare the per-signature roofline/MFU ``perf`` block of
run manifests (ISSUE 17).

The blocks come from ``mxnet_tpu._debug.perfmodel`` — every goodput run
manifest and every ``bench.py`` BENCH_MODEL manifest that executed a
tagged fused step carries one under ``manifest["perf"]`` (schema
``mxtpu.perf/1`` inside the ``mxtpu.goodput.run/1`` manifest). Like
``goodput_report``, this tool is deliberately dependency-free (stdlib
json only, no jax import): it must run on a laptop against manifests
rsync'd off a fleet.

Usage::

    python tools/perf_report.py RUN            # human-readable roofline
    python tools/perf_report.py --compare A B  # MFU regression verdict

``RUN``/``A``/``B`` are manifest paths or run directories containing
``manifest.json``. ``--compare`` treats A as the baseline and B as the
candidate and exits non-zero when a signature's MFU regresses past
threshold — the standing gate the ROADMAP item 4 campaign (fp8, remat)
is measured against.

The verdict is noise-robust by construction (the ``goodput_report``
discipline): signatures are joined by their STABLE compile-signature
tag (crc of the signature tuple, identical across processes for the
same program); when each side has exactly one signature they are
compared regardless of tag (a code change retraces under a new tag but
is still the same campaign); and an MFU drop must clear BOTH a
relative threshold and an absolute floor to fire — a 30% wobble on an
MFU of 0.003 from a microbench can never page anyone. Thresholds:
``--mfu-pct`` (default 10: relative MFU drop %), ``--min-mfu-abs``
(0.02: absolute MFU points), ``--median-pct``/``--min-median-abs-us``
(25 / 50: per-signature median step-time growth, the same pair
goodput_report uses run-wide).

Exit codes: 0 = no regression, 1 = regression past threshold,
2 = bad usage / unreadable manifest / no perf block to compare.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# keep in sync with mxnet_tpu/_debug/goodput.py + perfmodel.py (not
# imported: this tool must not drag the jax runtime in)
SCHEMA = "mxtpu.goodput.run/1"
PERF_SCHEMA = "mxtpu.perf/1"
BOUNDS = ("compute", "memory", "comm", "overhead")


def load_manifest(path):
    if os.path.isdir(path):
        path = os.path.join(path, "manifest.json")
    with open(path, encoding="utf-8") as f:
        m = json.load(f)
    if m.get("schema") != SCHEMA:
        raise ValueError("%s: schema %r is not %r (not a run manifest)"
                         % (path, m.get("schema"), SCHEMA))
    perf = m.get("perf")
    if perf is not None and perf.get("schema") != PERF_SCHEMA:
        raise ValueError("%s: perf block schema %r is not %r"
                         % (path, perf.get("schema"), PERF_SCHEMA))
    return m


def _sigs(m):
    return (m.get("perf") or {}).get("signatures") or {}


def _fmt(v, spec="%.4f"):
    return spec % v if isinstance(v, (int, float)) else "-"


def render(m):
    """One manifest -> a human-readable roofline report (lines)."""
    lines = ["perf %s  [%s]" % (m["run_id"], m.get("outcome", "open"))]
    perf = m.get("perf")
    if not perf:
        lines.append("  (no perf block: the run executed no tagged "
                     "fused step)")
        return lines
    a = perf.get("assumptions") or {}
    if a:
        lines.append("  model: %s  hbm %s GB/s  peaks %s" % (
            a.get("chip"), a.get("hbm_bw_GBps"),
            " ".join("%s=%s" % (k, v) for k, v in sorted(
                (a.get("peak_tflops") or {}).items()))))
    lines.append("  %-26s %6s %10s %7s %7s %8s %-9s %s" % (
        "signature", "steps", "med(us)", "MFU", "membw", "AI",
        "bound", "comp/mem/comm/ovh(us)"))
    sigs = _sigs(m)
    for sig in sorted(sigs, key=lambda s: -sigs[s].get("steps", 0)):
        r = sigs[sig]
        t = r.get("terms_s") or {}
        med = r.get("median_s")
        lines.append("  %-26s %6s %10s %7s %7s %8s %-9s %s" % (
            sig[:26], r.get("steps", 0),
            _fmt(med * 1e6 if med else None, "%.1f"),
            _fmt(r.get("mfu")), _fmt(r.get("membw_util")),
            _fmt(r.get("intensity"), "%.1f"), r.get("bound") or "-",
            "/".join(_fmt(t.get(b, 0.0) * 1e6, "%.1f")
                     for b in BOUNDS) if t else "-"))
        if r.get("collapses"):
            lines.append("  %-26s efficiency collapses: %d"
                         % ("", r["collapses"]))
    return lines


def _pairs(a, b):
    """(tag, baseline_row, candidate_row) join. Matched tags join by
    tag; when each side has exactly ONE signature, they join regardless
    (a retrace renames the tag, the campaign is the same program)."""
    sa, sb = _sigs(a), _sigs(b)
    common = sorted(set(sa) & set(sb))
    if common:
        return [(s, sa[s], sb[s]) for s in common]
    if len(sa) == 1 and len(sb) == 1:
        ta, tb = next(iter(sa)), next(iter(sb))
        return [("%s -> %s" % (ta, tb), sa[ta], sb[tb])]
    return []


def compare(a, b, mfu_pct=10.0, min_mfu_abs=0.02, median_pct=25.0,
            min_median_abs_us=50.0):
    """MFU regression verdict for candidate ``b`` against baseline
    ``a``. Returns (lines, regressed: bool, compared: int)."""
    lines = ["baseline  %s  [%s]" % (a["run_id"],
                                     a.get("outcome", "?")),
             "candidate %s  [%s]" % (b["run_id"],
                                     b.get("outcome", "?"))]
    regressed = False
    pairs = _pairs(a, b)
    for tag, ra, rb in pairs:
        ma, mb = ra.get("mfu"), rb.get("mfu")
        if isinstance(ma, (int, float)) and ma > 0 and \
                isinstance(mb, (int, float)):
            drop = ma - mb
            rel = 100.0 * drop / ma
            bad = rel > mfu_pct and drop > min_mfu_abs
            regressed |= bad
            lines.append(
                "%-11s %s MFU: %.4f -> %.4f (%+.1f%%; threshold "
                "-%.0f%% and -%.3f abs)" % (
                    "REGRESSION" if bad else "ok", tag, ma, mb, -rel,
                    mfu_pct, min_mfu_abs))
        else:
            lines.append("skip        %s MFU: missing" % tag)
        pa, pb = ra.get("median_s"), rb.get("median_s")
        if isinstance(pa, (int, float)) and pa > 0 and \
                isinstance(pb, (int, float)):
            rel = 100.0 * (pb - pa) / pa
            bad = rel > median_pct and \
                (pb - pa) * 1e6 > min_median_abs_us
            regressed |= bad
            lines.append(
                "%-11s %s median step: %.6fs -> %.6fs (%+.1f%%; "
                "threshold +%.0f%% and +%.0fus)" % (
                    "REGRESSION" if bad else "ok", tag, pa, pb, rel,
                    median_pct, min_median_abs_us))
        ba, bb = ra.get("bound"), rb.get("bound")
        if ba and bb and ba != bb:
            lines.append("note        %s roofline bound moved: "
                         "%s -> %s" % (tag, ba, bb))
    if not pairs:
        lines.append("skip        no comparable signatures "
                     "(baseline %d, candidate %d, none shared)"
                     % (len(_sigs(a)), len(_sigs(b))))
    lines.append("verdict: %s" % ("REGRESSION" if regressed else
                                  "no regression"))
    return lines, regressed, len(pairs)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="perf_report",
        description="Render / compare per-signature roofline+MFU "
                    "blocks of run manifests.")
    ap.add_argument("runs", nargs="+",
                    help="manifest path(s) or run director(ies)")
    ap.add_argument("--compare", action="store_true",
                    help="compare two runs: baseline candidate")
    ap.add_argument("--mfu-pct", type=float, default=10.0)
    ap.add_argument("--min-mfu-abs", type=float, default=0.02)
    ap.add_argument("--median-pct", type=float, default=25.0)
    ap.add_argument("--min-median-abs-us", type=float, default=50.0)
    args = ap.parse_args(argv)
    try:
        manifests = [load_manifest(p) for p in args.runs]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("perf_report: %s" % e, file=sys.stderr)
        return 2
    if args.compare:
        if len(manifests) != 2:
            print("perf_report: --compare takes exactly two runs "
                  "(baseline candidate)", file=sys.stderr)
            return 2
        if not _sigs(manifests[0]) and not _sigs(manifests[1]):
            print("perf_report: neither manifest carries a perf "
                  "block — nothing to compare", file=sys.stderr)
            return 2
        lines, regressed, _ = compare(
            manifests[0], manifests[1], mfu_pct=args.mfu_pct,
            min_mfu_abs=args.min_mfu_abs, median_pct=args.median_pct,
            min_median_abs_us=args.min_median_abs_us)
        print("\n".join(lines))
        return 1 if regressed else 0
    for m in manifests:
        print("\n".join(render(m)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
