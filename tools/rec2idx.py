"""Create an .idx index file for an existing RecordIO .rec file,
enabling random access via MXIndexedRecordIO.

ref: /root/reference/tools/rec2idx.py IndexCreator — reads through the
record stream, recording the byte offset of each record as
"<key>\\t<offset>\\n" lines.

Usage: python tools/rec2idx.py data.rec data.idx [--key-type int]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from mxnet_tpu.recordio import MXRecordIO  # noqa: E402


class IndexCreator(MXRecordIO):
    """Sequential pass over a .rec writing the byte offset of every
    record into an .idx sidecar (ref: tools/rec2idx.py IndexCreator)."""

    def __init__(self, uri, idx_path, key_type=int):
        self.key_type = key_type
        self.idx_path = idx_path
        self.fidx = None
        super().__init__(uri, "r")

    def open(self):
        super().open()
        self.fidx = open(self.idx_path, "w")

    def close(self):
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def create_index(self):
        """ref: rec2idx.py IndexCreator.create_index."""
        counter = 0
        pre_time = __import__("time").time()
        while True:
            pos = self.tell()
            cont = self.read()
            if cont is None:
                break
            key = self.key_type(counter)
            self.fidx.write("%s\t%d\n" % (str(key), pos))
            counter += 1
            if counter % 1000 == 0:
                cur_time = __import__("time").time()
                if cur_time - pre_time > 2:
                    print("time: %s  count: %d" % (cur_time, counter))
                    pre_time = cur_time
        return counter


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Make an index file for a RecordIO file "
        "(ref: tools/rec2idx.py)")
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", help="path for the .idx output")
    p.add_argument("--key-type", choices=["int", "str"], default="int")
    args = p.parse_args(argv)
    creator = IndexCreator(args.record, args.index,
                           int if args.key_type == "int" else str)
    n = creator.create_index()
    creator.close()
    print("wrote %d index entries to %s" % (n, args.index))
    return 0


if __name__ == "__main__":
    sys.exit(main())
