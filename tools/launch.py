#!/usr/bin/env python
"""Multi-process job launcher.

TPU-native analog of the reference's cluster launcher (ref: tools/launch.py
→ dmlc-core tracker): where the reference's tracker spawns
scheduler + servers + workers and wires them with DMLC_ROLE /
DMLC_PS_ROOT_URI / DMLC_NUM_WORKER / DMLC_NUM_SERVER env vars, this spawns
N worker processes wired to one jax.distributed coordinator (process 0's
host:port) with MXTPU_COORDINATOR / MXTPU_NUM_PROCS / MXTPU_PROC_ID.
There is no separate server role: parameter aggregation is XLA collectives
over ICI/DCN (Gloo on CPU), so every process is a worker
(SURVEY.md §5 "distributed communication backend").

Usage (mirrors the reference CLI):
    python tools/launch.py -n 4 python train_script.py --args...
    python tools/launch.py -n 4 --launcher local python train.py

`--launcher ssh -H hostfile` distributes over hosts via ssh, one process
per host line (the reference's ssh launcher analog); `local` (default)
runs all processes on this machine — the CI harness for dist tests, like
the reference's `--launcher local` used by tests/nightly/test_all.sh.
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def launch_local(n, command, env_extra=None):
    """Spawn n local worker processes; returns the list of exit codes."""
    port = _free_port()
    coordinator = "127.0.0.1:%d" % port
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update({
            "MXTPU_COORDINATOR": coordinator,
            "MXTPU_NUM_PROCS": str(n),
            "MXTPU_PROC_ID": str(rank),
            # DMLC-compatible aliases so reference-era scripts that read
            # these still see a consistent world
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(rank),
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        procs.append(subprocess.Popen(command, env=env))
    codes = []
    try:
        for p in procs:
            codes.append(p.wait())
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        raise
    return codes


def launch_ssh(n, hosts, command):
    """One process per host over ssh (ref: dmlc-core ssh tracker)."""
    assert len(hosts) >= 1, "ssh launcher needs a non-empty hostfile"
    coordinator = "%s:%d" % (hosts[0], 29400)
    procs = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        envs = " ".join("%s=%s" % kv for kv in [
            ("MXTPU_COORDINATOR", coordinator),
            ("MXTPU_NUM_PROCS", str(n)),
            ("MXTPU_PROC_ID", str(rank)),
        ])
        remote = "cd %s && env %s %s" % (
            shlex.quote(os.getcwd()), envs,
            " ".join(shlex.quote(c) for c in command))
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no", host,
                                       remote]))
    return [p.wait() for p in procs]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job "
                    "(ref: tools/launch.py)")
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes "
                             "(ref: launch.py -n num_workers)")
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for the ssh launcher")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the worker command to run")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.launcher == "local":
        codes = launch_local(args.num_workers, args.command)
    else:
        if not args.hostfile:
            parser.error("the ssh launcher requires -H/--hostfile")
        with open(args.hostfile) as f:
            hosts = [ln.strip() for ln in f if ln.strip()]
        codes = launch_ssh(args.num_workers, hosts, args.command)
    bad = [c for c in codes if c != 0]
    if bad:
        print("launch failed: exit codes %s" % codes, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
