"""mxlint dataflow rules (MX014-MX017): whole-program analyses over the
project model.

Where the PR 3 rules check what a LINE looks like, these check what the
PROGRAM does: reachability from trace entry points (MX014), the env-var
contract across code + docs + the signature-token registry (MX015),
buffer liveness across donating calls (MX016), and the global lexical
lock-nesting digraph (MX017). MX014/MX015/MX017 are *project* rules —
``core.run`` hands them the aggregated :class:`project.ProjectModel`
instead of per-file ASTs; MX016 is intraprocedural, so it stays a
per-file rule (sharing the one parse) with a cached cross-file table of
donating ops. See docs/LINTING.md for the catalog entries.
"""
from __future__ import annotations

import ast
import os
import re

from .core import Finding
from . import project as _project


# ---------------------------------------------------------------------------
# MX014 — traced-ambient-state capture
# ---------------------------------------------------------------------------

# Telemetry/introspection modules: their ambient state — clocks, event
# tags (PID), recorder switches, ring caps, the allocation-ledger knobs
# — gates what gets RECORDED about a program, never a value that flows
# into a traced graph (no function here returns array data to a
# caller). Since ISSUE 13 weaves ledger/detector hooks into code the
# call graph reaches from trace entries, the whole dump/metrics
# subsystem LOOKS trace-reachable statically; exempting these modules
# from all three clauses keeps the rule aimed at its real target —
# compute modules whose env reads shape cached executables (the PR 9
# bug class). MX007 polices their clock discipline and MX015 their env
# contract regardless.
_TELEMETRY_MODULES = (
    "mxnet_tpu/profiler.py",
    "mxnet_tpu/storage.py",  # introspection + the allocation ledger
    "mxnet_tpu/_debug/",
    "mxnet_tpu/pallas_kernels/_compile_attr.py",  # compile attribution
)

# (module path -> builder functions whose nested closures are traced)
_TRACE_HOSTS = {
    "mxnet_tpu/gluon/fused_step.py": ("_build", "_packed_apply_fn"),
    "mxnet_tpu/gluon/block.py": ("make_pure_forward",),
    "mxnet_tpu/ndarray/register.py": ("_build_traced", "_flush_impl"),
}


class MX014TracedAmbientState:
    """Functions reachable from a trace entry point (``register.invoke``
    op bodies, fused-step loss/step closures, Pallas kernels, optimizer
    ``step_fn``s, bulk-segment flushes) execute INSIDE a jitted program:
    whatever ambient state they read — ``os.environ``, env-derived
    module globals, wall/monotonic clocks, host RNG — is baked into the
    cached executable at trace time and silently replayed on every
    later hit. That is the bug class PR 9's review pass caught by hand
    (kernel-routing envs missing from the dispatch key); the static
    contract is: an env var read on a traced path must be registered in
    the compile-signature token registry
    (``register.register_signature_token``), and clocks/host-RNG must
    not appear at all (thread them in as operands)."""

    code = "MX014"
    summary = "traced code reads ambient state outside the token registry"
    kind = "python"
    project = True

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    # -- entry points --------------------------------------------------

    def _is_op_body(self, mf, fn):
        for dn, _ln in fn.decorators:
            leaf = dn.split(".")[-1]
            if leaf != "register":
                continue
            if "." in dn:
                root = dn.split(".")[0]
                target = mf.imports.get(root, root)
                if target.endswith("registry") or target.endswith("ops"):
                    return True
            else:
                target = mf.imports.get(dn, "")
                if target.endswith("registry.register"):
                    return True
        return False

    def entries(self, model):
        keys = []
        for key, fn in model.functions.items():
            path, qual = key
            if not path.startswith("mxnet_tpu/"):
                continue
            mf = model.modules[path]
            if self._is_op_body(mf, fn):
                keys.append(key)
                continue
            if path.startswith("mxnet_tpu/pallas_kernels/"):
                name = path.rsplit("/", 1)[-1]
                if name != "__init__.py" and not name.startswith("_") \
                        and qual != "<module>":
                    keys.append(key)
                    continue
            last = qual.split(".")[-1]
            if last in ("step_fn", "step_fn_multi_precision") \
                    and "<locals>" not in qual:
                keys.append(key)
                continue
            hosts = _TRACE_HOSTS.get(path, ())
            for host in hosts:
                if (".%s.<locals>." % host) in qual \
                        or qual.startswith("%s.<locals>." % host):
                    keys.append(key)
                    break
        return keys

    # -- the check -----------------------------------------------------

    def check_project(self, model):
        tokens = set(model.signature_tokens())
        out = []
        for key in sorted(model.reachable(self.entries(model))):
            path, qual = key
            if not path.startswith("mxnet_tpu/") \
                    or path == "mxnet_tpu/base.py":
                # the getenv choke point itself: its internal
                # os.environ read is attributed to each CALLER (the
                # extractor records getenv() call sites as env reads)
                continue
            fn = model.functions[key]
            mf = model.modules[path]
            telemetry = any(path.startswith(t)
                            for t in _TELEMETRY_MODULES)
            if not telemetry:
                for kind, name, ln, family in fn.env_reads:
                    label = name if isinstance(name, str) else (
                        family if family else "<computed>")
                    if isinstance(name, str) and name in tokens:
                        continue
                    out.append(Finding(
                        self.code, path, ln,
                        "env read of %r inside traced code (reachable "
                        "from a trace entry via %s) — the value is "
                        "baked into the cached executable; register it "
                        "with register.register_signature_token so "
                        "flipping it recompiles, or hoist the read out "
                        "of the traced path" % (label, qual)))
                for akind, dn, ln in fn.ambient:
                    what = "clock" if akind == "clock" else "host RNG"
                    out.append(Finding(
                        self.code, path, ln,
                        "%s read (%s) inside traced code (reachable "
                        "from a trace entry via %s) — traces bake the "
                        "value at compile time and replay it forever; "
                        "thread it in as an operand (clocks) or use "
                        "the framework key plumbing (RNG)"
                        % (what, dn, qual)))
            if telemetry:
                continue

            def _telemetry_target(target_mf):
                return any(target_mf.path.startswith(t)
                           for t in _TELEMETRY_MODULES)

            for ref, ln in fn.refs:
                if "." in ref:
                    alias, attr = ref.split(".", 1)
                    target = model.by_name.get(
                        mf.imports.get(alias, ""))
                    if target and not _telemetry_target(target) and \
                            attr in target.env_globals and \
                            target.env_globals[attr] not in tokens:
                        out.append(self._global_finding(
                            path, ln, ref, target.env_globals[attr],
                            qual))
                elif ref in mf.env_globals and \
                        mf.env_globals[ref] not in tokens:
                    out.append(self._global_finding(
                        path, ln, ref, mf.env_globals[ref], qual))
        return out

    def _global_finding(self, path, ln, ref, env, qual):
        return Finding(
            self.code, path, ln,
            "read of env-derived global %r (from %s) inside traced "
            "code (reachable via %s) — same stale-replay hazard as a "
            "direct env read; register %s as a signature token or "
            "thread the value as an operand" % (ref, env, qual, env))


# ---------------------------------------------------------------------------
# MX015 — env-var contract sync
# ---------------------------------------------------------------------------

_DOC_NAME_RE = re.compile(r"`([A-Z][A-Z0-9_]{2,})`")


class MX015EnvContract:
    """Every env read in ``mxnet_tpu/`` goes through the ``base.getenv``
    choke point (computed names through ``getenv_dynamic(family=...)``),
    and every name read is documented in docs/ENV_VARS.md. Helper
    wrappers that take the name as a parameter are resolved ONE level
    through the call graph (the watchdog/flightrec ``_env_float(name)``
    idiom), so the contract follows the dataflow, not the spelling.
    Registered signature tokens must be documented too."""

    code = "MX015"
    summary = "env read bypasses base.getenv or is undocumented"
    kind = "python"
    project = True

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    _doc_cache = None  # (repo_root, frozenset | None)

    def _documented(self):
        from . import core
        cached = self._doc_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        doc_path = os.path.join(core.REPO_ROOT, "docs", "ENV_VARS.md")
        try:
            with open(doc_path, encoding="utf-8") as f:
                names = frozenset(_DOC_NAME_RE.findall(f.read()))
        except OSError:
            names = None  # no contract file: skip the doc clause
        self._doc_cache = (core.REPO_ROOT, names)
        return names

    def check_project(self, model):
        docs = self._documented()
        out = []

        def check_doc(name, path, ln, how):
            if docs is not None and name not in docs:
                out.append(Finding(
                    self.code, path, ln,
                    "env var %r is read in code (%s) but missing from "
                    "docs/ENV_VARS.md — document it (default + "
                    "consumer) or remove the read" % (name, how)))

        for mf in sorted(model.modules.values(), key=lambda m: m.path):
            if not mf.path.startswith("mxnet_tpu/") \
                    or mf.path == "mxnet_tpu/base.py":
                continue
            for qual in sorted(mf.functions):
                fn = mf.functions[qual]
                for kind, name, ln, family in fn.env_reads:
                    if kind == _project.READ_DIRECT:
                        out.append(Finding(
                            self.code, mf.path, ln,
                            "direct os.environ/os.getenv read — route "
                            "through the base.getenv choke point "
                            "(base.getenv_dynamic for computed names) "
                            "so the env contract stays analyzable"))
                    elif kind == _project.READ_DYNAMIC:
                        if family is None:
                            out.append(Finding(
                                self.code, mf.path, ln,
                                "getenv_dynamic without a literal "
                                "family= — the computed name must "
                                "declare the documented ENV_VARS.md "
                                "row it derives from"))
                        else:
                            check_doc(family, mf.path, ln,
                                      "dynamic family")
                    else:  # READ_GETENV
                        if isinstance(name, str):
                            check_doc(name, mf.path, ln, "getenv")
                        elif isinstance(name, tuple):
                            self._resolve_param(
                                model, mf, fn, name[1], ln, check_doc,
                                out)
                        else:
                            out.append(Finding(
                                self.code, mf.path, ln,
                                "base.getenv with a computed name — "
                                "use getenv_dynamic(family=...) and "
                                "document the family"))
        for name, (path, ln) in sorted(
                model.signature_tokens().items()):
            check_doc(name, path, ln, "signature token")
        return out

    def _resolve_param(self, model, mf, fn, param, ln, check_doc, out):
        """getenv(name) where name is a parameter of the enclosing
        helper: resolve the literal one level up through every caller."""
        shift = 1 if fn.params and fn.params[0] in ("self", "cls") else 0
        try:
            idx = fn.params.index(param) - shift
        except ValueError:
            idx = None
        callers = model.callers_of((mf.path, fn.qualname))
        if not callers:
            default = fn.param_defaults.get(param)
            if isinstance(default, str):
                check_doc(default, mf.path, ln, "helper default")
            else:
                out.append(Finding(
                    self.code, mf.path, ln,
                    "getenv(%s) takes its name from parameter %r with "
                    "no resolvable caller — pass a literal, or use "
                    "getenv_dynamic(family=...)" % (param, param)))
            return
        for (cpath, _cqual), (dn, cln, args_lits, kw_lits) in callers:
            lit = None
            if param in kw_lits:
                lit = kw_lits[param]
            elif idx is not None and 0 <= idx < len(args_lits):
                lit = args_lits[idx]
            elif isinstance(fn.param_defaults.get(param), str):
                lit = fn.param_defaults[param]
            if isinstance(lit, str):
                check_doc(lit, cpath, cln,
                          "via helper %s" % fn.qualname)
            else:
                out.append(Finding(
                    self.code, cpath, cln,
                    "%s() forwards a computed env name to getenv — "
                    "the contract checker cannot resolve it; pass a "
                    "literal or use getenv_dynamic(family=...)" % dn))


# ---------------------------------------------------------------------------
# MX016 — use-after-donation
# ---------------------------------------------------------------------------

class MX016UseAfterDonation:
    """Intraprocedural liveness across donating calls. Two donation
    sources: (a) registry ops with ``inplace=`` positions (the
    ``*_update`` optimizer family — the NDArray wrapper re-adopts the
    state arg itself, so only PRE-call aliases of it — ``x``,
    ``x.copy()``, ``x.detach()``, all O(1) buffer shares — go stale),
    and (b) local ``jax.jit(..., donate_argnums=...)`` programs (raw
    arrays: the args THEMSELVES go stale). Reading a stale binding
    after the call is a silent no-op on the CPU tier-1 suite but a
    runtime crash on TPU — only static analysis can gate it here. A
    reassignment or an ``_adopt_fused(...)`` re-adoption clears the
    binding; snapshot with ``.asnumpy()`` BEFORE the call if you need
    pre-update values."""

    code = "MX016"
    summary = "read of a donated buffer binding after the donating call"
    kind = "python"

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    # -- the donating-op table (one parse per run, like MX013) ---------

    _table_cache = None  # (repo_root, {op name: (positions,)})

    def _table(self):
        from . import core
        cached = self._table_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        table = {}
        ops_dir = os.path.join(core.REPO_ROOT, "mxnet_tpu", "ops")
        try:
            names = sorted(os.listdir(ops_dir))
        except OSError:
            names = []
        for name in names:
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(ops_dir, name),
                          encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    dfn = dec.func
                    leaf = dfn.id if isinstance(dfn, ast.Name) else (
                        dfn.attr if isinstance(dfn, ast.Attribute)
                        else "")
                    if leaf != "register":
                        continue
                    opname = None
                    if dec.args and isinstance(dec.args[0],
                                               ast.Constant):
                        opname = dec.args[0].value
                    pos = None
                    for kw in dec.keywords:
                        if kw.arg == "inplace" and isinstance(
                                kw.value, (ast.Tuple, ast.List)):
                            pos = tuple(
                                e.value for e in kw.value.elts
                                if isinstance(e, ast.Constant))
                    if opname and pos:
                        table[str(opname)] = pos
        self._table_cache = (core.REPO_ROOT, table)
        return table

    # -- per-function linear simulation --------------------------------

    def check(self, path, src, tree, parents):
        table = self._table()
        out = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_fn(path, node, table))
        return out

    @staticmethod
    def _pos(n):
        return (n.lineno, n.col_offset)

    @staticmethod
    def _end(n):
        return (getattr(n, "end_lineno", n.lineno),
                getattr(n, "end_col_offset", n.col_offset))

    def _check_fn(self, path, fnnode, table):
        jit_donors = {}   # local name -> donated positions
        events = []       # (pos, kind, payload)

        own_body = [n for n in ast.walk(fnnode)
                    if not isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                    or n is fnnode]
        # exclude nodes belonging to NESTED defs (their dataflow is
        # their own; closures over donated names are beyond this rule)
        nested = [n for n in ast.walk(fnnode)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                  and n is not fnnode]
        skip = set()
        for nd in nested:
            for sub in ast.walk(nd):
                skip.add(id(sub))
        for n in own_body:
            if id(n) in skip:
                continue
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                tname = n.targets[0].id
                v = n.value
                root = None
                if isinstance(v, ast.Name):
                    root = v.id
                elif isinstance(v, ast.Call) and \
                        isinstance(v.func, ast.Attribute) and \
                        v.func.attr in ("copy", "detach") and \
                        isinstance(v.func.value, ast.Name):
                    root = v.func.value.id
                donate = self._jit_donate(v)
                if donate is not None:
                    jit_donors[tname] = donate
                # anchored at the statement END so the RHS's own reads
                # are processed first: `w = w.copy()` after a donation
                # must flag the read of `w` before clearing the binding
                events.append((self._end(n), "assign", (tname, root)))
            elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], (ast.Tuple, ast.List)):
                # tuple-unpack rebind — `w, s = jfn(w, s)` — clears
                # each Name target (the documented clean idiom)
                for t in n.targets[0].elts:
                    if isinstance(t, ast.Name):
                        events.append((self._end(n), "assign",
                                       (t.id, None)))
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                # `w += 1` READS w (Store ctx on the node, but the
                # operation loads the old buffer first)
                events.append((self._pos(n), "read", n.target.id))
            elif isinstance(n, ast.Call):
                rec = self._donating_call(n, table, jit_donors)
                if rec is not None:
                    events.append((self._end(n), "donate", rec))
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "_adopt_fused":
                    names = [a.id for a in n.args
                             if isinstance(a, ast.Name)]
                    if isinstance(n.func.value, ast.Name):
                        names.append(n.func.value.id)
                    # anchored at the CALL start so the re-adoption
                    # clears the binding before its own arg reads
                    events.append((self._pos(n), "adopt", names))
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                events.append((self._pos(n), "read", n.id))

        # at one position: reads first (RHS before its own assign),
        # adopt clears before its arg reads would flag, donate before
        # an enclosing assign (so `w = jfn(w)` poisons w, then the
        # rebind immediately clears it)
        events.sort(key=lambda e: (e[0], {"read": 0, "adopt": 1,
                                          "donate": 2,
                                          "assign": 3}[e[1]]))
        aliases = {}
        poisoned = {}  # name -> (donor description, donate lineno)
        out = []
        for pos, kind, payload in events:
            if kind == "assign":
                tname, root = payload
                poisoned.pop(tname, None)
                if root is not None:
                    aliases[tname] = aliases.get(root, root)
                else:
                    aliases.pop(tname, None)
            elif kind == "adopt":
                for nm in payload:
                    poisoned.pop(nm, None)
            elif kind == "donate":
                desc, arg_names, rebinds = payload
                roots = set(arg_names)
                stale = set()
                for al, rt in aliases.items():
                    if rt in roots or al in roots:
                        stale.add(al)
                if not rebinds:
                    stale.update(roots)
                else:
                    # the wrapper re-adopts the args themselves; only
                    # pre-call buffer shares stay stale
                    stale.difference_update(arg_names)
                for nm in stale:
                    poisoned.setdefault(nm, (desc, pos[0]))
            elif kind == "read" and payload in poisoned:
                desc, dln = poisoned[payload]
                out.append(Finding(
                    self.code, path, pos[0],
                    "%r aliases a buffer donated at line %d (%s) — "
                    "reading it is a stale-buffer crash on TPU (and a "
                    "silent wrong answer under interpret); re-adopt "
                    "via _adopt_fused, reassign, or snapshot with "
                    ".asnumpy() BEFORE the donating call"
                    % (payload, dln, desc)))
                del poisoned[payload]  # one finding per binding
        return out

    @staticmethod
    def _jit_donate(v):
        """donate_argnums tuple for `jax.jit(f, donate_argnums=...)`
        (any alias spelled `*.jit`), else None."""
        if not (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "jit"):
            return None
        for kw in v.keywords:
            if kw.arg == "donate_argnums":
                val = kw.value
                if isinstance(val, (ast.Tuple, ast.List)):
                    return tuple(e.value for e in val.elts
                                 if isinstance(e, ast.Constant))
                if isinstance(val, ast.Constant):
                    return (val.value,)
        return None

    def _donating_call(self, n, table, jit_donors):
        """(description, [donated arg Names], rebinds) or None."""
        f = n.func
        positions = None
        rebinds = True
        desc = None
        if isinstance(f, ast.Attribute) and f.attr in table:
            positions, desc = table[f.attr], "%s, inplace args" % f.attr
        elif isinstance(f, ast.Name):
            if f.id in table:
                positions, desc = table[f.id], \
                    "%s, inplace args" % f.id
            elif f.id in jit_donors:
                positions, desc, rebinds = jit_donors[f.id], \
                    "jitted program %s, donate_argnums" % f.id, False
        if positions is None:
            return None
        if any(isinstance(a, ast.Starred) for a in n.args):
            return None  # *operands calls: positions unknowable
        names = []
        for i in positions:
            if isinstance(i, int) and i < len(n.args) and \
                    isinstance(n.args[i], ast.Name):
                names.append(n.args[i].id)
        if not names:
            return None
        return (desc, names, rebinds)


# ---------------------------------------------------------------------------
# MX018 — unledgered device-buffer creation
# ---------------------------------------------------------------------------

# Hot modules under the allocation-ledger contract (ISSUE 13): the
# dispatch/creation core, input placement, the kvstore transport, and
# the fused-step adoption path.
_LEDGER_HOT = (
    "mxnet_tpu/ndarray/",
    "mxnet_tpu/io/",
    "mxnet_tpu/kvstore_async.py",
    "mxnet_tpu/gluon/parameter.py",
    "mxnet_tpu/gluon/fused_step.py",
)
# jnp.asarray creates device buffers too, but flagging it everywhere
# would drown the rule in index/scalar conversions — it is a creator
# only in the transport/input modules, where an asarray IS a fresh
# resident payload buffer.
_ASARRAY_SCOPED = ("mxnet_tpu/kvstore_async.py", "mxnet_tpu/io/")
# The ledger choke points (storage.py) + the cached hot alias spelling.
_LEDGER_CHOKES = frozenset((
    "ledger_register", "ledger_register_tree", "ledger_retire",
    "pending_append", "_ctx_place", "_LEDGER_ACT", "_place",
))


class MX018UnledgeredBufferCreation:
    """Device-buffer creation in the hot modules — ``jax.device_put``
    anywhere, ``jnp.asarray`` in the transport/input modules — must
    flow through the tagged allocation ledger (ISSUE 13): the creating
    function calls a ``storage.ledger_*`` choke point (or a helper one
    resolvable call away that does), so every resident buffer carries a
    category tag and an OOM post-mortem can name what was resident. A
    creation site the ledger cannot see is anonymous HBM — exactly the
    blind spot the ledger exists to close. Waive only buffers that are
    provably transient or re-registered by their adopter, with the
    justification saying which."""

    code = "MX018"
    summary = "device-buffer creation site misses the allocation ledger"
    kind = "python"
    project = True

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    @staticmethod
    def _leaf(dn):
        return dn.rsplit(".", 1)[-1]

    def _creator_calls(self, path, fn):
        out = []
        asarray_ok = any(path.startswith(p) for p in _ASARRAY_SCOPED)
        for dn, ln, _a, _k in fn.calls:
            leaf = self._leaf(dn)
            if leaf == "device_put":
                out.append((dn, ln))
            elif asarray_ok and leaf == "asarray" and (
                    dn.split(".")[0] == "jnp"
                    or dn.endswith("jax.numpy.asarray")):
                # np.asarray makes HOST arrays — only the jnp spelling
                # creates a device buffer
                out.append((dn, ln))
        return out

    def _calls_choke(self, fn):
        return any(self._leaf(dn) in _LEDGER_CHOKES
                   for dn, _ln, _a, _k in fn.calls)

    def _registered(self, model, key, fn, depth=1):
        """The function (or a callee one resolvable hop away, or a
        nested closure it builds) reaches a ledger choke point."""
        if self._calls_choke(fn):
            return True
        if depth <= 0:
            return False
        for nxt in model.edges_from(key):
            nfn = model.functions.get(nxt)
            if nfn is not None and self._registered(model, nxt, nfn,
                                                    depth - 1):
                return True
        return False

    def check_project(self, model):
        out = []
        for key in sorted(model.functions):
            path, qual = key
            if not any(path.startswith(p) for p in _LEDGER_HOT):
                continue
            fn = model.functions[key]
            creators = self._creator_calls(path, fn)
            if not creators:
                continue
            if self._registered(model, key, fn):
                continue
            for dn, ln in creators:
                out.append(Finding(
                    self.code, path, ln,
                    "%s() in %s creates a device buffer the allocation "
                    "ledger never sees — register it at a "
                    "storage.ledger_* choke point (tag taxonomy in "
                    "docs/OBSERVABILITY.md) or waive with a "
                    "justification naming why the buffer is transient "
                    "or re-registered by its adopter" % (dn, qual)))
        return out


# ---------------------------------------------------------------------------
# MX017 — static lock-order graph
# ---------------------------------------------------------------------------

class MX017StaticLockOrder:
    """The lexical ``with <named_lock>:`` nesting graph across
    ``mxnet_tpu/`` must be acyclic: an edge pair A->B / B->A is the
    same lock-order inversion the runtime detector
    (``_debug/locktrace.py``, MXNET_DEBUG_LOCKS=1) reports from real
    interleavings — this is the static half of the PR 3 enforcement
    pair, and ``tools/mxlint --lock-graph`` cross-checks the two
    (zero contradictions on a clean tree)."""

    code = "MX017"
    summary = "cycle in the lexical named-lock nesting graph"
    kind = "python"
    project = True

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    def check_project(self, model):
        edges = model.lock_graph(
            lambda p: p.startswith("mxnet_tpu/"))
        out = []
        for cyc in _project.find_cycles(edges):
            pair_sites = []
            for a, b in zip(cyc, cyc[1:]):
                pair_sites.extend(edges.get((a, b), []))
            site = sorted(pair_sites)[0] if pair_sites else ("", 0)
            out.append(Finding(
                self.code, site[0], site[1],
                "lock-order cycle %s in the lexical with-nesting "
                "graph — two threads interleaving these paths can "
                "deadlock; impose one global order (see "
                "docs/LINTING.md, `--lock-graph` prints the digraph; "
                "other edges of this cycle: %s)"
                % (" -> ".join(cyc),
                   ", ".join("%s:%d" % s for s in sorted(pair_sites)))))
        return out


# ---------------------------------------------------------------------------
# --lock-graph: static graph dump + runtime-trace diff
# ---------------------------------------------------------------------------

def lock_graph_report(model, runtime_dump=None):
    """Build the --lock-graph report dict.

    ``runtime_dump`` is a ``locktrace.report()`` JSON payload (or the
    ``profiler.metrics()['locks']`` embedding): ``order_edges`` as
    ``"a->b"`` strings. Contradictions = a pair ordered one way
    statically and the other way at runtime (i.e. any cycle in the
    UNION graph that neither graph has alone); static-only /
    runtime-only edges are coverage info, not errors — lexical nesting
    cannot see cross-function acquisition chains, and a runtime trace
    only covers the paths the suite drove."""
    in_scope = lambda p: p.startswith("mxnet_tpu/")  # noqa: E731
    static_edges = model.lock_graph(in_scope)
    static_set = set(static_edges)
    report = {
        "locks": sorted(model.lock_nodes(in_scope)
                        | {n for e in static_set for n in e}),
        "static_edges": sorted("%s->%s" % e for e in static_set),
        "static_sites": {"%s->%s" % e: ["%s:%d" % s for s in sites]
                         for e, sites in sorted(static_edges.items())},
        "static_cycles": [" -> ".join(c)
                          for c in _project.find_cycles(static_set)],
    }
    if runtime_dump is not None:
        rt = set()
        for e in runtime_dump.get("order_edges", ()):
            a, _, b = e.partition("->")
            if a and b:
                rt.add((a, b))
        rt_cycles = _project.find_cycles(rt)
        # a union cycle lying entirely inside ONE graph is that graph's
        # own cycle (reported above/below); only a cycle that NEEDS
        # edges from both graphs is a cross-graph ordering
        # contradiction — classification by edge membership, so cycle
        # rotation/entry-point never misclassifies
        contradictions = []
        for c in _project.find_cycles(static_set | rt):
            cyc_edges = set(zip(c, c[1:]))
            if not cyc_edges <= static_set and not cyc_edges <= rt:
                contradictions.append(c)
        report.update({
            "runtime_edges": sorted("%s->%s" % e for e in rt),
            "runtime_cycles": [" -> ".join(c) for c in rt_cycles],
            "static_only": sorted("%s->%s" % e
                                  for e in static_set - rt),
            "runtime_only": sorted("%s->%s" % e
                                   for e in rt - static_set),
            "contradictions": [" -> ".join(c)
                               for c in contradictions],
        })
    return report


# ---------------------------------------------------------------------------
# MX019 — metrics() provider doc contract
# ---------------------------------------------------------------------------

_PROVIDER_DOC_RE = re.compile(
    r"metrics\(\)\[['\"]([A-Za-z_][A-Za-z0-9_]*)['\"]\]")


class MX019MetricsProviderDocs:
    """Every ``profiler.register_stats_provider("<name>", ...)`` call
    publishes a ``metrics()['<name>']`` section scrapers and operators
    build on — an undocumented section is an API nobody can find and a
    doc rot vector when it changes. The MX015 idiom applied to the
    metrics surface: each registered section name must appear in
    docs/OBSERVABILITY.md as ``metrics()['<name>']`` (either quote
    style), and the name must be a literal so the contract stays
    statically checkable."""

    code = "MX019"
    summary = "metrics() provider section undocumented in " \
              "OBSERVABILITY.md"
    kind = "python"
    project = True

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    _doc_cache = None  # (repo_root, frozenset | None)

    def _documented(self):
        from . import core
        cached = self._doc_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        doc_path = os.path.join(core.REPO_ROOT, "docs",
                                "OBSERVABILITY.md")
        try:
            with open(doc_path, encoding="utf-8") as f:
                names = frozenset(_PROVIDER_DOC_RE.findall(f.read()))
        except OSError:
            names = None  # no contract file: skip the doc clause
        self._doc_cache = (core.REPO_ROOT, names)
        return names

    def check_project(self, model):
        docs = self._documented()
        out = []
        for mf in sorted(model.modules.values(), key=lambda m: m.path):
            if not mf.path.startswith("mxnet_tpu/"):
                continue
            for qual in sorted(mf.functions):
                for dn, ln, args_lits, kw_lits in \
                        mf.functions[qual].calls:
                    if dn.split(".")[-1] != "register_stats_provider":
                        continue
                    name = kw_lits.get("name")
                    if name is None and args_lits:
                        name = args_lits[0]
                    if name is None:
                        out.append(Finding(
                            self.code, mf.path, ln,
                            "register_stats_provider with a computed "
                            "section name — pass a string literal so "
                            "the metrics() doc contract stays "
                            "checkable"))
                    elif docs is not None and name not in docs:
                        out.append(Finding(
                            self.code, mf.path, ln,
                            "metrics() provider section %r is "
                            "registered here but never documented — "
                            "add a metrics()['%s'] section to "
                            "docs/OBSERVABILITY.md (what the keys "
                            "mean, who feeds them) or drop the "
                            "registration" % (name, name)))
        return out


# ---------------------------------------------------------------------------
# MX022 — jit sites invisible to the compile-attribution registry
# ---------------------------------------------------------------------------

# Hot modules under the compile-attribution contract (ISSUE 18): the
# operator dispatch cache, the cached-graph executor, the fused step,
# the optimizer update jits, the sharded/overlapped train steps, the
# transformer bench harness, and the Pallas kernels. A compile these
# modules trigger that ``profiler.compile_stats()`` cannot see is a
# silent recompile vector — exactly what the registry (and hlolint's
# capture feed riding on it) exists to close.
_COMPILE_HOT = (
    "mxnet_tpu/ndarray/register.py",
    "mxnet_tpu/gluon/block.py",
    "mxnet_tpu/gluon/fused_step.py",
    "mxnet_tpu/optimizer/optimizer.py",
    "mxnet_tpu/parallel/train.py",
    "mxnet_tpu/parallel/transformer.py",
    "mxnet_tpu/pallas_kernels/",
)
# The registry choke points: the profiler entry, the fused-step
# recording seam, and the one-shot first-call probe spellings
# (register._compile_probe / ShardedTrainStep._compile_probe) whose
# bodies feed record_compile.
_COMPILE_CHOKES = frozenset((
    "record_compile", "_record_compile", "_compile_probe",
))


class MX022UnregisteredCompile:
    """Every ``jax.jit``/``pjit`` in the hot modules must be visible to
    the compile-attribution registry: the creating function reaches
    ``profiler.record_compile`` (directly, one resolvable call away, or
    from a direct caller that records on its behalf), so recompiles
    show up in ``compile_stats()`` and the hlolint capture feed instead
    of vanishing into step-time noise. A jit the registry cannot see is
    an unattributable compile — the retracing class of bug MX005 flags
    lexically, enforced here at the accounting layer. Waive only
    harness/bench jits whose callers time and account the compile
    themselves, with the justification saying where."""

    code = "MX022"
    summary = "jit site invisible to the compile-attribution registry"
    kind = "python"
    project = True

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    @staticmethod
    def _is_jit(mf, dn):
        parts = dn.split(".")
        if len(parts) == 1:
            # from jax import jit [as alias]
            return mf.imports.get(dn) in ("jax.jit", "jax.pjit")
        if parts[-1] not in ("jit", "pjit"):
            return False
        root = mf.imports.get(parts[0], parts[0])
        return root == "jax" or root.startswith("jax.")

    def _jit_sites(self, mf, fn):
        # a call `jax.jit(...)` also lands in refs at the same line
        # (the attribute load) — dedup by line, calls win the label
        sites = {}
        for dn, ln, _a, _k in fn.calls:
            if self._is_jit(mf, dn):
                sites.setdefault(ln, dn)
        for name, ln in fn.refs:
            if self._is_jit(mf, name):
                sites.setdefault(ln, name)
        return sorted(sites.items())

    def _calls_choke(self, fn):
        return any(dn.rsplit(".", 1)[-1] in _COMPILE_CHOKES
                   for dn, _ln, _a, _k in fn.calls)

    def _registered(self, model, key, fn, depth=1):
        """The function (or a callee one resolvable hop away, or a
        nested closure it builds) reaches a registry choke point."""
        if self._calls_choke(fn):
            return True
        if depth <= 0:
            return False
        for nxt in model.edges_from(key):
            nfn = model.functions.get(nxt)
            if nfn is not None and self._registered(model, nxt, nfn,
                                                    depth - 1):
                return True
        return False

    def _caller_records(self, model, key):
        """A DIRECT caller records on the builder's behalf (the
        fused_step._dispatch -> _build -> _record_compile shape)."""
        for ck, _rec in model.callers_of(key):
            cfn = model.functions.get(ck)
            if cfn is not None and self._calls_choke(cfn):
                return True
        return False

    def check_project(self, model):
        out = []
        for key in sorted(model.functions):
            path, qual = key
            if not any(path.startswith(p) for p in _COMPILE_HOT):
                continue
            fn = model.functions[key]
            mf = model.modules[path]
            sites = self._jit_sites(mf, fn)
            if not sites:
                continue
            if self._registered(model, key, fn):
                continue
            if self._caller_records(model, key):
                continue
            for ln, dn in sites:
                out.append(Finding(
                    self.code, path, ln,
                    "%s in %s builds a compiled program the "
                    "compile-attribution registry never sees — reach "
                    "profiler.record_compile within one call (the "
                    "_compile_probe idiom), record from the direct "
                    "caller, or waive with a justification naming who "
                    "accounts this compile (docs/LINTING.md)"
                    % (dn, qual)))
        return out


# ---------------------------------------------------------------------------
# MX023 — zero-badput knobs: documented AND signature-registered
# ---------------------------------------------------------------------------

# Modules where the zero-badput knob contract is enforced: the
# checkpoint/recovery plane, the fused step + its persistent compile
# cache, and the kvstore peer-snapshot plane (ISSUE 19).
_ZERO_BADPUT_MODULES = (
    "mxnet_tpu/parallel/elastic.py",
    "mxnet_tpu/gluon/fused_step.py",
    "mxnet_tpu/gluon/compile_cache.py",
    "mxnet_tpu/kvstore_async.py",
    "mxnet_tpu/kvstore_server.py",
)

# Name families owned by the zero-badput plane. Any knob in these
# families flips behavior that either shapes a compiled program (the
# compile cache key must see it) or changes what a checkpoint contains
# (a resume under a different setting must recompile/re-key, not
# silently reuse) — so reading one obliges BOTH contracts below.
_ZERO_BADPUT_PREFIXES = ("MXTPU_CKPT_", "MXTPU_COMPILE_CACHE",
                        "MXTPU_PEER_")

# Cadence-only knobs: they schedule WHEN work happens (publish every N
# steps), never what any traced graph or compile key contains — the
# documentation clause still applies (via MX015), but signature-token
# registration would only force spurious recompiles on cadence tuning.
_CADENCE_ONLY = frozenset((
    "MXTPU_PEER_SNAPSHOT_EVERY",
))


class MX023ZeroBadputKnobContract:
    """Every env knob of the zero-badput plane (``MXTPU_CKPT_*``,
    ``MXTPU_COMPILE_CACHE*``, ``MXTPU_PEER_*``) read in the
    checkpoint/cache/peer modules must be documented in
    docs/ENV_VARS.md AND — unless it is a pure cadence knob — appear in
    the signature-token registry (``register_signature_token``), so
    flipping it lands later compiles on a fresh signature instead of
    silently replaying a program compiled under the old setting. MX015
    already enforces the choke-point + documentation half for all of
    ``mxnet_tpu/``; this rule adds the registration half that makes the
    persistent compile cache safe to key off the token snapshot."""

    code = "MX023"
    summary = "zero-badput env knob undocumented or not a signature token"
    kind = "python"
    project = True

    def scope(self, path):
        # broad: scope() gates project-model fact extraction, and the
        # token clause needs register.py's registrations in the model;
        # check_project restricts findings to _ZERO_BADPUT_MODULES
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    _doc_cache = None  # (repo_root, frozenset | None)

    def _documented(self):
        from . import core
        cached = self._doc_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        doc_path = os.path.join(core.REPO_ROOT, "docs", "ENV_VARS.md")
        try:
            with open(doc_path, encoding="utf-8") as f:
                names = frozenset(_DOC_NAME_RE.findall(f.read()))
        except OSError:
            names = None  # no contract file: skip the doc clause
        self._doc_cache = (core.REPO_ROOT, names)
        return names

    @staticmethod
    def _owned(name):
        return isinstance(name, str) and \
            name.startswith(_ZERO_BADPUT_PREFIXES)

    def check_project(self, model):
        docs = self._documented()
        tokens = model.signature_tokens()
        out = []
        for mf in sorted(model.modules.values(), key=lambda m: m.path):
            if mf.path not in _ZERO_BADPUT_MODULES:
                continue
            for qual in sorted(mf.functions):
                fn = mf.functions[qual]
                for _kind, name, ln, family in fn.env_reads:
                    lit = name if isinstance(name, str) else family
                    if not self._owned(lit):
                        continue
                    if docs is not None and lit not in docs:
                        out.append(Finding(
                            self.code, mf.path, ln,
                            "zero-badput knob %r is read here but "
                            "missing from docs/ENV_VARS.md — document "
                            "it (default + consumer + what it gates)"
                            % (lit,)))
                    if lit not in tokens and lit not in _CADENCE_ONLY:
                        out.append(Finding(
                            self.code, mf.path, ln,
                            "zero-badput knob %r changes what a "
                            "compiled/checkpointed step means but is "
                            "not a registered signature token — add "
                            "register_signature_token(%r, ...) so the "
                            "compile cache and retrace keys see it "
                            "(or list it in _CADENCE_ONLY with why)"
                            % (lit, lit)))
        return out


# ---------------------------------------------------------------------------
# MX024 — wire-opcode contract: literal, dispatched, documented
# ---------------------------------------------------------------------------

# The one module that owns the async-PS wire protocol.
_WIRE_MODULE = "mxnet_tpu/kvstore_async.py"

# Backticked opcode names in the RESILIENCE.md opcode table.
_OPCODE_DOC_RE = re.compile(r"`(_OP_[A-Z0-9_]+)`")


class MX024WireOpcodeContract:
    """Every ``_OP_*`` wire-opcode constant in ``kvstore_async.py`` must
    be (a) an integer **literal** — a computed opcode breaks the
    length-gated interop story because old peers can't be audited
    against a value that only exists at runtime; (b) **dispatched** in
    ``AsyncPSServer._handle`` (an ``op == _OP_X`` comparison) — an
    opcode the server never checks is either dead wire surface or a
    handler someone forgot, and either way an unknown-opcode ``_RE_ERR``
    to a live client; and (c) **documented** in docs/RESILIENCE.md's
    opcode table — the normative registry the resend-safety and
    length-gating contracts live in. ISSUE 20 satellite: the journal +
    failover + fencing work tripled the opcode surface; this rule keeps
    the registry honest as it grows."""

    code = "MX024"
    summary = "wire opcode computed, undispatched, or undocumented"
    kind = "python"
    project = True

    def scope(self, path):
        return path == _WIRE_MODULE

    _doc_cache = None  # (repo_root, frozenset | None)

    def _documented(self):
        from . import core
        cached = self._doc_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        doc_path = os.path.join(core.REPO_ROOT, "docs", "RESILIENCE.md")
        try:
            with open(doc_path, encoding="utf-8") as f:
                names = frozenset(_OPCODE_DOC_RE.findall(f.read()))
        except OSError:
            names = None  # no contract file: skip the doc clause
        self._doc_cache = (core.REPO_ROOT, names)
        return names

    @staticmethod
    def _dispatched_names(tree):
        """``_OP_*`` names compared against inside AsyncPSServer._handle."""
        out = set()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == "AsyncPSServer"):
                continue
            for item in node.body:
                if not (isinstance(item, ast.FunctionDef)
                        and item.name == "_handle"):
                    continue
                for sub in ast.walk(item):
                    if not isinstance(sub, ast.Compare):
                        continue
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Name) \
                                and n.id.startswith("_OP_"):
                            out.add(n.id)
        return out

    def check_project(self, model):
        from . import core
        if _WIRE_MODULE not in model.modules:
            return []
        src_path = os.path.join(core.REPO_ROOT, _WIRE_MODULE)
        try:
            with open(src_path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return []
        declared = {}   # name -> (lineno, is_literal_int)
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                # _OP_NAMES is the display-name map, not an opcode
                if isinstance(tgt, ast.Name) \
                        and tgt.id.startswith("_OP_") \
                        and tgt.id != "_OP_NAMES":
                    lit = isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, int)
                    declared[tgt.id] = (node.lineno, lit)
        dispatched = self._dispatched_names(tree)
        docs = self._documented()
        out = []
        for name in sorted(declared):
            ln, lit = declared[name]
            if not lit:
                out.append(Finding(
                    self.code, _WIRE_MODULE, ln,
                    "wire opcode %s is computed, not an integer literal "
                    "— the length-gated interop contract needs opcode "
                    "values auditable from the source" % (name,)))
            if name not in dispatched:
                out.append(Finding(
                    self.code, _WIRE_MODULE, ln,
                    "wire opcode %s is never checked in "
                    "AsyncPSServer._handle — add the dispatch arm (a "
                    "live client sending it gets unknown-opcode "
                    "_RE_ERR) or delete the constant" % (name,)))
            if docs is not None and name not in docs:
                out.append(Finding(
                    self.code, _WIRE_MODULE, ln,
                    "wire opcode %s is missing from the "
                    "docs/RESILIENCE.md opcode table — document its "
                    "fields, resend-safety, and length-gating"
                    % (name,)))
        return out


DATAFLOW_RULES = (
    MX014TracedAmbientState(),
    MX015EnvContract(),
    MX016UseAfterDonation(),
    MX017StaticLockOrder(),
    MX018UnledgeredBufferCreation(),
    MX019MetricsProviderDocs(),
    MX022UnregisteredCompile(),
    MX023ZeroBadputKnobContract(),
    MX024WireOpcodeContract(),
)
