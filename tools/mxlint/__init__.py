"""mxlint: framework-invariant static analysis for mxnet_tpu.

The AST/text half of the enforcement pair (the runtime half is
``mxnet_tpu/_debug/locktrace.py``): 17 framework-specific rules. The
lexical set (MX001-MX013) keeps the PR 1-2 invariants — single
dispatch choke point, guarded telemetry, locked shared state,
API_BEGIN/API_END on the C ABI — true across future PRs the way the
reference wires cpplint/pylint into ci/; the whole-program set
(MX014-MX017, ``dataflow.py`` over the ``project.py`` model) checks
the *dataflow* bug classes recent PRs actually hit: traced code
capturing ambient state outside the compile-signature token registry,
env-contract drift between code and docs/ENV_VARS.md, use-after-
donation, and lock-order cycles (``--lock-graph`` diffs the static
digraph against a locktrace runtime dump).

    python -m tools.mxlint                 # lint mxnet_tpu src tests
    python -m tools.mxlint mxnet_tpu/io    # lint a subtree
    python -m tools.mxlint --rule MX003 .  # one rule
    python -m tools.mxlint --jobs 4        # parallel per-file phase
    python -m tools.mxlint --lock-graph --runtime-dump locks.json

See docs/LINTING.md for the rule catalog, the waiver idiom, the
baseline workflow, and the dataflow-engine notes. tests/test_lint.py
runs this over the tree in tier-1 and fails on any unwaived finding.
"""
from .core import Finding, build_model, load_baseline, main, \
    parse_waivers, run
from .project import ProjectModel
from .rules import ALL_RULES

__all__ = ["Finding", "ALL_RULES", "run", "main", "parse_waivers",
           "load_baseline", "build_model", "ProjectModel"]
