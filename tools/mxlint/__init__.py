"""mxlint: framework-invariant static analysis for mxnet_tpu.

The AST/text half of the enforcement pair (the runtime half is
``mxnet_tpu/_debug/locktrace.py``): ~8 framework-specific rules that
keep the PR 1-2 invariants — single dispatch choke point, guarded
telemetry, locked shared state, API_BEGIN/API_END on the C ABI — true
across future PRs the way the reference wires cpplint/pylint into ci/.

    python -m tools.mxlint                 # lint mxnet_tpu src tests
    python -m tools.mxlint mxnet_tpu/io    # lint a subtree
    python -m tools.mxlint --rule MX003 .  # one rule

See docs/LINTING.md for the rule catalog, the waiver idiom, and the
baseline workflow. tests/test_lint.py runs this over the tree in
tier-1 and fails on any unwaived finding.
"""
from .core import Finding, load_baseline, main, parse_waivers, run
from .rules import ALL_RULES

__all__ = ["Finding", "ALL_RULES", "run", "main", "parse_waivers",
           "load_baseline"]
