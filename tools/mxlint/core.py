"""mxlint driver: file walking, waivers, baseline, CLI.

Waiver idiom (Python and C++):

    # mxlint: disable=MX003 (GIL-atomic counter bumps; lock would cost
    #         more than the race on the dispatch hot path)
    // mxlint: disable=MX006 (no-throw body: plain pointer bookkeeping)

A waiver suppresses the listed codes on its own line and the line
directly below it (so it can sit above the offending statement). MX003
additionally honors a waiver on the flagged container's *definition*
line — declare once at the definition that unlocked mutation is
intentional instead of waiving every mutation site. A waiver without a
parenthesized justification is itself reported (MX000): the point is a
reviewed reason next to every exemption.

Baseline: ``tools/mxlint/baseline.json`` records known findings as
``{code, path, line}`` triples that don't fail the run (the cpplint
NOLINT-file escape hatch for bulk-adopting a rule). The checked-in
baseline is empty — every pre-existing violation was fixed or waived —
and should stay that way; regenerate with ``--write-baseline`` only
when bulk-introducing a new rule.
"""
from __future__ import annotations

import ast
import json
import os
import sys

from tools import lintcommon as _common
from tools.lintcommon import Finding  # re-exported public API

# MXLINT_REPO_ROOT: re-root the analysis (scope checks, doc/catalog
# lookups) onto another tree — tooling/test hook, not needed in-repo
REPO_ROOT = os.environ.get("MXLINT_REPO_ROOT") or os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

_WAIVER_RE, _FILE_WAIVER_RE = _common.waiver_regexes(
    "mxlint", r"MX\d{3}")

# directories never worth walking
_SKIP_DIRS = {".git", "__pycache__", "build", "blib", ".pytest_cache",
              "node_modules"}


def parse_waivers(src):
    """(line waivers, file waivers, bad waivers). Line waivers are
    {line -> set(codes)}; a waiver covers its own line and the next
    one. ``disable-file=`` waives a code for the whole file — for
    files whose entire design is the exemption (document the design in
    the justification). Waivers lacking a justification are returned
    as bad."""
    return _common.parse_waivers(src, _WAIVER_RE, _FILE_WAIVER_RE)


def _iter_files(paths):
    for top in paths:
        ab = top if os.path.isabs(top) else os.path.join(REPO_ROOT, top)
        if os.path.isfile(ab):
            yield ab
            continue
        for root, dirs, files in os.walk(ab):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if f.endswith((".py", ".cc", ".h")):
                    yield os.path.join(root, f)


def _rel(path):
    return os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")


def _analyze_file(abspath, rel, rules_or_codes, want_facts):
    """Per-file phase: ONE read + ONE parse shared by every per-file
    rule and by the project-model fact extraction. Returns a picklable
    record (``--jobs`` runs this in worker processes):

        (rel, findings, waivers, file_waivers, bad, facts)

    or None when the file is out of scope / unreadable.

    ``rules_or_codes``: rule INSTANCES (serial path — custom rule
    objects outside ALL_RULES run as-is) or a set of code strings
    (parallel path — workers re-derive the instances from ALL_RULES,
    which is why run() keeps custom per-file rules on the serial
    path)."""
    from .rules import ALL_RULES, _parents
    from . import project as _project
    if rules_or_codes is None or all(isinstance(r, str)
                                     for r in rules_or_codes):
        rules = [r for r in ALL_RULES
                 if rules_or_codes is None or r.code in rules_or_codes]
    else:
        rules = list(rules_or_codes)
    per_file = [r for r in rules if not getattr(r, "project", False)
                and r.scope(rel)]
    project_rules = [r for r in rules if getattr(r, "project", False)]
    want_facts = want_facts and rel.endswith(".py") and \
        any(r.scope(rel) for r in project_rules)
    if not per_file and not want_facts:
        return None
    try:
        with open(abspath, encoding="utf-8", errors="replace") as f:
            src = f.read()
    except OSError:
        return None
    waivers, file_waivers, bad = parse_waivers(src)
    findings = []
    tree = parents = facts = None
    if rel.endswith(".py"):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            bad.append((e.lineno or 0, ["<parse:%s>" % e.msg]))
            return (rel, findings, waivers, file_waivers, bad, None)
        parents = _parents(tree)
        if want_facts:
            facts = _project.extract(rel, tree, parents=parents)
    for rule in per_file:
        if rule.kind == "python" and tree is None:
            continue
        if rule.kind == "cc" and rel.endswith(".py"):
            continue
        findings.extend(rule.check(rel, src, tree, parents))
    return (rel, findings, waivers, file_waivers, bad, facts)


def _analyze_parallel(files, rule_codes, want_facts, jobs):
    import multiprocessing as mp
    try:
        ctx = mp.get_context("fork")
    except ValueError:   # no fork (non-POSIX): stay serial
        return [_analyze_file(ab, rel, rule_codes, want_facts)
                for ab, rel in files]
    chunk = max(1, len(files) // (jobs * 4) or 1)
    with ctx.Pool(jobs) as pool:
        return pool.starmap(
            _analyze_file,
            [(ab, rel, rule_codes, want_facts) for ab, rel in files],
            chunksize=chunk)


def run(paths, rules=None, baseline=None, jobs=1):
    """Lint ``paths`` (repo-relative or absolute files/dirs).

    Two phases: a per-file phase (one parse per file, shared by every
    lexical rule and the project-model extraction; ``jobs > 1``
    parallelizes it across processes) and a project phase where the
    dataflow rules (MX014/MX015/MX017) query the aggregated
    :class:`project.ProjectModel`.

    Returns (unwaived findings, waived count, baselined count,
    bad-waiver findings)."""
    from .rules import ALL_RULES
    from . import project as _project
    rules = list(ALL_RULES if rules is None else rules)
    rule_codes = {r.code for r in rules}
    project_rules = [r for r in rules if getattr(r, "project", False)]
    if baseline is None:
        baseline = load_baseline()
    base_keys = _common.baseline_keys(baseline)

    files = [(ab, _rel(ab)) for ab in _iter_files(paths)]
    # workers rebuild rule instances from ALL_RULES by code — ANY
    # custom rule object outside the registry (per-file OR project:
    # project rules gate fact extraction via scope()) forces the
    # serial path so results never differ between jobs settings
    known = {id(r) for r in ALL_RULES}
    all_known = all(id(r) in known for r in rules)
    if jobs and jobs > 1 and len(files) > 1 and all_known:
        results = _analyze_parallel(files, rule_codes,
                                    bool(project_rules), jobs)
    else:
        results = [_analyze_file(ab, rel, rules,
                                 bool(project_rules))
                   for ab, rel in files]

    findings, bad_waivers, facts = [], [], []
    waiver_maps = {}  # rel -> (line waivers, file waivers)
    for res in results:
        if res is None:
            continue
        rel, file_findings, waivers, file_waivers, bad, fact = res
        waiver_maps[rel] = (waivers, file_waivers)
        findings.extend(file_findings)
        for line, codes in bad:
            if codes and codes[0].startswith("<parse:"):
                bad_waivers.append(Finding(
                    "MX000", rel, line, "file does not parse: %s"
                    % codes[0][7:-1]))
            else:
                bad_waivers.append(Finding(
                    "MX000", rel, line,
                    "waiver for %s has no justification — write "
                    "`# mxlint: disable=CODE (reason)`"
                    % ",".join(codes)))
        if fact is not None:
            facts.append(fact)

    if project_rules:
        model = _project.ProjectModel(facts)
        for rule in project_rules:
            findings.extend(rule.check_project(model))

    kept, n_waived, n_baselined = _common.apply_waivers_and_baseline(
        findings, waiver_maps, base_keys)
    return kept, n_waived, n_baselined, bad_waivers


def build_model(paths):
    """Parse+extract a ProjectModel over ``paths`` (no rule checks) —
    the ``--lock-graph`` entry point and a library hook for tools.
    Always serial: extraction over a tree this size is sub-second."""
    from . import project as _project
    files = [(ab, _rel(ab)) for ab in _iter_files(paths)
             if ab.endswith(".py")]
    facts = []
    for ab, rel in files:
        try:
            with open(ab, encoding="utf-8", errors="replace") as f:
                src = f.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        facts.append(_project.extract(rel, tree))
    return _project.ProjectModel(facts)


def load_baseline(path=BASELINE_PATH):
    return _common.load_baseline(path)


def write_baseline(findings, path=BASELINE_PATH):
    _common.write_baseline(
        findings, path,
        "Known findings exempt from failing mxlint. Keep empty; see "
        "docs/LINTING.md.")


def _emit(findings, fmt):
    _common.emit(findings, fmt, "mxlint")


def _lock_graph_main(args):
    from . import dataflow as _dataflow
    paths = args.paths or ["mxnet_tpu"]
    model = build_model(paths)
    dump = None
    if args.runtime_dump:
        with open(args.runtime_dump, encoding="utf-8") as f:
            dump = json.load(f)
        if "order_edges" not in dump and "locks" in dump and \
                isinstance(dump["locks"], dict):
            dump = dump["locks"]  # profiler.metrics() embedding
    rep = _dataflow.lock_graph_report(model, runtime_dump=dump)
    print(json.dumps(rep, indent=2, sort_keys=True))
    bad = list(rep.get("static_cycles", ()))
    bad += rep.get("runtime_cycles", ())
    bad += rep.get("contradictions", ())
    for c in bad:
        print("lock-graph: CYCLE %s" % c, file=sys.stderr)
    print("lock-graph: %d locks, %d static edges%s, %d cycle%s/"
          "contradiction%s" % (
              len(rep["locks"]), len(rep["static_edges"]),
              ", %d runtime edges" % len(rep["runtime_edges"])
              if "runtime_edges" in rep else "",
              len(bad), "" if len(bad) == 1 else "s",
              "" if len(bad) == 1 else "s"), file=sys.stderr)
    return 1 if bad else 0


def main(argv=None):
    import argparse
    from .rules import ALL_RULES
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="Framework-invariant static analysis "
                    "(docs/LINTING.md has the rule catalog).")
    ap.add_argument("paths", nargs="*",
                    default=["mxnet_tpu", "src", "tests"],
                    help="files/dirs to lint (default: mxnet_tpu src "
                         "tests)")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to specific rule codes (repeatable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parallel per-file analysis processes")
    ap.add_argument("--format", choices=("text", "github"),
                    default="text",
                    help="finding output format (github = ::error "
                         "workflow annotations)")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the static lexical lock-nesting "
                         "digraph (JSON) instead of linting; non-zero "
                         "exit on a cycle")
    ap.add_argument("--runtime-dump", metavar="FILE", default=None,
                    help="with --lock-graph: diff the static graph "
                         "against a locktrace.report() JSON dump "
                         "(cycles + ordering contradictions fail)")
    args = ap.parse_args(argv)

    if args.lock_graph:
        # the default lint roots include tests/; the lock-order
        # contract is scoped to the framework tree
        if args.paths == ["mxnet_tpu", "src", "tests"]:
            args.paths = ["mxnet_tpu"]
        return _lock_graph_main(args)

    rules = None
    if args.rule:
        rules = [r for r in ALL_RULES if r.code in set(args.rule)]
    findings, n_waived, n_baselined, bad = run(
        args.paths, rules=rules, jobs=args.jobs)

    if args.write_baseline:
        write_baseline(findings)
        print("baseline: recorded %d findings" % len(findings))
        return 0

    _emit(findings + bad, args.format)
    print(_common.summary_line("mxlint", findings, n_waived,
                               n_baselined, bad), file=sys.stderr)
    return 1 if findings or bad else 0
