"""mxlint driver: file walking, waivers, baseline, CLI.

Waiver idiom (Python and C++):

    # mxlint: disable=MX003 (GIL-atomic counter bumps; lock would cost
    #         more than the race on the dispatch hot path)
    // mxlint: disable=MX006 (no-throw body: plain pointer bookkeeping)

A waiver suppresses the listed codes on its own line and the line
directly below it (so it can sit above the offending statement). MX003
additionally honors a waiver on the flagged container's *definition*
line — declare once at the definition that unlocked mutation is
intentional instead of waiving every mutation site. A waiver without a
parenthesized justification is itself reported (MX000): the point is a
reviewed reason next to every exemption.

Baseline: ``tools/mxlint/baseline.json`` records known findings as
``{code, path, line}`` triples that don't fail the run (the cpplint
NOLINT-file escape hatch for bulk-adopting a rule). The checked-in
baseline is empty — every pre-existing violation was fixed or waived —
and should stay that way; regenerate with ``--write-baseline`` only
when bulk-introducing a new rule.
"""
from __future__ import annotations

import ast
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

_WAIVER_RE = re.compile(
    r"(?:#|//)\s*mxlint:\s*disable=((?:MX\d{3})(?:\s*,\s*MX\d{3})*)"
    r"\s*(\(.+)?")
_FILE_WAIVER_RE = re.compile(
    r"(?:#|//)\s*mxlint:\s*disable-file=((?:MX\d{3})(?:\s*,\s*MX\d{3})*)"
    r"\s*(\(.+)?")

# directories never worth walking
_SKIP_DIRS = {".git", "__pycache__", "build", "blib", ".pytest_cache",
              "node_modules"}


class Finding:
    __slots__ = ("code", "path", "line", "message", "extra_waiver_lines")

    def __init__(self, code, path, line, message,
                 extra_waiver_lines=()):
        self.code = code
        self.path = path
        self.line = line
        self.message = message
        # additional lines whose waivers also suppress this finding
        # (MX003: the container's definition line)
        self.extra_waiver_lines = tuple(extra_waiver_lines)

    def __repr__(self):
        return "%s:%d: %s %s" % (self.path, self.line, self.code,
                                 self.message)


def parse_waivers(src):
    """(line waivers, file waivers, bad waivers). Line waivers are
    {line -> set(codes)}; a waiver covers its own line and the next
    one. ``disable-file=`` waives a code for the whole file — for
    files whose entire design is the exemption (document the design in
    the justification). Waivers lacking a justification are returned
    as bad."""
    waivers = {}
    file_waivers = set()
    bad = []
    for i, line in enumerate(src.splitlines(), start=1):
        fm = _FILE_WAIVER_RE.search(line)
        m = _WAIVER_RE.search(line) if fm is None else None
        if fm is not None:
            codes = {c.strip() for c in fm.group(1).split(",")}
            file_waivers.update(codes)
            reason = (fm.group(2) or "").strip("() \t")
        elif m is not None:
            codes = {c.strip() for c in m.group(1).split(",")}
            reason = (m.group(2) or "").strip("() \t")
            waivers.setdefault(i, set()).update(codes)
            waivers.setdefault(i + 1, set()).update(codes)
        else:
            continue
        if not reason:
            bad.append((i, sorted(codes)))
    return waivers, file_waivers, bad


def _iter_files(paths):
    for top in paths:
        ab = top if os.path.isabs(top) else os.path.join(REPO_ROOT, top)
        if os.path.isfile(ab):
            yield ab
            continue
        for root, dirs, files in os.walk(ab):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if f.endswith((".py", ".cc", ".h")):
                    yield os.path.join(root, f)


def _rel(path):
    return os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")


def run(paths, rules=None, baseline=None):
    """Lint ``paths`` (repo-relative or absolute files/dirs).

    Returns (unwaived findings, waived count, baselined count,
    bad-waiver findings)."""
    from .rules import ALL_RULES
    from .rules import _parents
    rules = list(ALL_RULES if rules is None else rules)
    if baseline is None:
        baseline = load_baseline()
    base_keys = {(b["code"], b["path"], b.get("line")) for b in baseline}

    findings, bad_waivers = [], []
    n_waived = n_baselined = 0
    for abspath in _iter_files(paths):
        rel = _rel(abspath)
        active = [r for r in rules if r.scope(rel)]
        if not active:
            continue
        try:
            with open(abspath, encoding="utf-8", errors="replace") as f:
                src = f.read()
        except OSError:
            continue
        waivers, file_waivers, bad = parse_waivers(src)
        for line, codes in bad:
            bad_waivers.append(Finding(
                "MX000", rel, line,
                "waiver for %s has no justification — write "
                "`# mxlint: disable=CODE (reason)`" % ",".join(codes)))
        tree = parents = None
        if rel.endswith(".py"):
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                bad_waivers.append(Finding(
                    "MX000", rel, e.lineno or 0,
                    "file does not parse: %s" % e.msg))
                continue
            parents = _parents(tree)
        for rule in active:
            if rule.kind == "python" and tree is None:
                continue
            if rule.kind == "cc" and rel.endswith(".py"):
                continue
            for fi in rule.check(rel, src, tree, parents):
                lines = (fi.line,) + fi.extra_waiver_lines
                if fi.code in file_waivers or \
                        any(fi.code in waivers.get(l, ()) for l in lines):
                    n_waived += 1
                elif (fi.code, fi.path, fi.line) in base_keys or \
                        (fi.code, fi.path, None) in base_keys:
                    n_baselined += 1
                else:
                    findings.append(fi)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings, n_waived, n_baselined, bad_waivers


def load_baseline(path=BASELINE_PATH):
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f).get("findings", [])
    except (OSError, ValueError):
        return []


def write_baseline(findings, path=BASELINE_PATH):
    data = {
        "comment": "Known findings exempt from failing mxlint. Keep "
                   "empty; see docs/LINTING.md.",
        "findings": [{"code": f.code, "path": f.path, "line": f.line}
                     for f in findings],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def main(argv=None):
    import argparse
    from .rules import ALL_RULES
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="Framework-invariant static analysis "
                    "(docs/LINTING.md has the rule catalog).")
    ap.add_argument("paths", nargs="*",
                    default=["mxnet_tpu", "src", "tests"],
                    help="files/dirs to lint (default: mxnet_tpu src "
                         "tests)")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to specific rule codes (repeatable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline")
    args = ap.parse_args(argv)

    rules = None
    if args.rule:
        rules = [r for r in ALL_RULES if r.code in set(args.rule)]
    findings, n_waived, n_baselined, bad = run(args.paths, rules=rules)

    if args.write_baseline:
        write_baseline(findings)
        print("baseline: recorded %d findings" % len(findings))
        return 0

    for f in findings + bad:
        print("%s:%d: %s %s" % (f.path, f.line, f.code, f.message))
    summary = "mxlint: %d finding%s (%d waived, %d baselined)" % (
        len(findings), "" if len(findings) == 1 else "s", n_waived,
        n_baselined)
    if bad:
        summary += ", %d bad waiver%s" % (len(bad),
                                          "" if len(bad) == 1 else "s")
    print(summary, file=sys.stderr)
    return 1 if findings or bad else 0
